"""Headline benchmark: GPT-2 pretraining throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric is tokens/sec/chip for a GPT-2 (124M) training step, the
BASELINE.json headline.  vs_baseline = achieved MFU / 0.35 (the north
star: >=35% MFU GPT-2 pretrain with no CUDA in the wheel).

Tuned config (measured on v5e, round 2): batch 16, pallas flash
attention with whole-sequence blocks (ops/flash_attention.py), remat on
(HBM-bandwidth-bound regime: smaller live activations beat recompute
cost), plain fused cross entropy.  Round-1 dense-attention config was
73.7k tok/s (32% MFU); the flash kernel lifts it ~1.5x.
"""

import json
import time

import jax
import jax.numpy as jnp

from ray_tpu.train.config import PEAK_FLOPS_BY_GEN as _PEAK_FLOPS
from ray_tpu.util import goodput as _goodput


def _gpt2_bench_setup():
    """Shared model/optimizer setup for the GPT-2 benches: GPT-2 small
    on a real chip, a scaled-down copy on CPU so the bench stays
    runnable anywhere (vs_baseline is only meaningful on TPU).
    Returns (cfg, on_tpu, state, optimizer, loss_fn, one_step)."""
    from ray_tpu.models.gpt2 import (GPT2Config, gpt2_init, gpt2_loss_fn)
    from ray_tpu.train.train_step import (TrainState, make_optimizer,
                                          make_train_step)

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if on_tpu:
        cfg = GPT2Config(n_layer=12, n_head=12, d_model=768, d_ff=3072,
                         vocab_size=50257, max_seq=1024, remat=True,
                         attn_impl="flash")
    else:
        cfg = GPT2Config(vocab_size=2048, n_layer=4, n_head=8, d_model=256,
                         d_ff=1024, max_seq=256, remat=True)

    params = gpt2_init(cfg, jax.random.PRNGKey(0))
    optimizer = make_optimizer(total_steps=1000)
    state = jax.device_put(TrainState.create(params, optimizer))

    def loss_fn(p, b):
        # 256-wide fused chunked xent (models/gpt2.py _chunked_xent
        # custom_vjp): measured best on-chip — the whole-logits path
        # pays ~3.3 GB of fp32 logits traffic per direction.
        return gpt2_loss_fn(cfg, p, b,
                            loss_chunk=256 if on_tpu else 0)

    return cfg, on_tpu, state, optimizer, loss_fn, \
        make_train_step(loss_fn, optimizer)


def main() -> None:
    import os

    cfg, on_tpu, state, optimizer, loss_fn, one_step = \
        _gpt2_bench_setup()
    batch, steps, reps = (16, 20, 3) if on_tpu else (4, 3, 1)
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (batch, cfg.max_seq + 1), 0,
                                cfg.vocab_size, jnp.int32)

    # The measured loop runs INSIDE one jit (lax.scan over steps): a
    # host-free training loop is the TPU-idiomatic shape AND the only
    # honest timing through an async dispatch tunnel — sync via
    # device_get of the scalar loss (block_until_ready is not a reliable
    # barrier on the axon relay platform).
    def run(state, tokens, n):
        def body(s, _):
            s, m = one_step(s, {"tokens": tokens})
            return s, m["loss"]
        state, losses = jax.lax.scan(body, state, None, length=n)
        return state, losses[-1]

    runner = jax.jit(run, static_argnums=(2,))
    ledger = _goodput.reset()
    # Warm up with the SAME step count (static arg => per-n executable;
    # timing a fresh n would measure compilation, not training).
    with ledger.phase("compile"):
        _, loss = runner(state, tokens, steps)
        _ = jax.device_get(loss)

    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        with ledger.phase("compute"):
            _, loss = runner(state, tokens, steps)
            _ = jax.device_get(loss)
        elapsed = time.perf_counter() - t0
        best = max(best, batch * cfg.max_seq * steps / elapsed)

    tok_s = best
    flops_per_token = cfg.flops_per_token()
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = _PEAK_FLOPS.get(gen, _PEAK_FLOPS["v5e"])
    mfu = tok_s * flops_per_token / peak if on_tpu else 0.0
    # Telemetry-plane smoke check: a bench run must emit a non-empty
    # goodput summary whose fractions sum to ~1.0, so the goodput
    # ledger can't silently rot (it has no other standalone exercise).
    # Explicit raise, not assert — must survive `python -O`.
    gp = ledger.snapshot()
    fracs = ledger.fractions()
    if gp["seconds"].get("compute", 0.0) <= 0.0 \
            or gp["seconds"].get("compile", 0.0) <= 0.0:
        raise RuntimeError(
            f"empty goodput summary from bench run: {gp}")
    if abs(sum(fracs.values()) - 1.0) >= 1e-6:
        raise RuntimeError(f"goodput fractions don't normalize: {fracs}")
    # Automated step decomposition (util/xprof): forward / backward /
    # optimizer seconds via state-carried scans — the measurement
    # MFU_ANALYSIS.md performs by hand, now a bench output every run.
    from ray_tpu.util import xprof as _xprof

    decomp = _xprof.measure_step_decomposition(
        loss_fn, optimizer, state, {"tokens": tokens},
        steps=steps, reps=reps,
        flops_per_step=batch * cfg.max_seq * flops_per_token)
    decomp_out = {
        "forward_s": round(decomp["forward_s"], 6),
        "backward_s": round(decomp["backward_s"], 6),
        "optimizer_s": round(decomp["optimizer_s"], 6),
        "full_step_s": round(decomp["full_step_s"], 6),
        "shares": {k: round(v, 4)
                   for k, v in decomp["shares"].items()},
    }
    if on_tpu and "of_peak" in decomp:
        # Of-peak ratios only mean something against a real chip's
        # peak; on CPU the resolved TPU peak would print noise.
        decomp_out["of_peak"] = {k: round(v, 4)
                                 for k, v in decomp["of_peak"].items()}
    out = {
        "metric": "gpt2_124m_pretrain_tokens_per_sec_per_chip"
        if on_tpu else "gpt2_scaled_cpu_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
        "goodput": {p: round(f, 4) for p, f in fracs.items()},
        "decomposition": decomp_out,
    }
    print(json.dumps(out))
    # The decomposition row rides along under --record: optimizer
    # share is the "optimizer is ~free" MFU_ANALYSIS claim as a
    # regression-guarded number (lower is better — a growing share
    # means the update stopped overlapping/fusing).
    _maybe_record(out, extra_rows=[
        {"benchmark": "gpt2_step_optimizer_share",
         "value": round(decomp["shares"]["optimizer"], 4),
         "unit": "fraction", "higher_is_better": False}])


def data_pipeline() -> None:
    """--data-pipeline: GPT-2 pretraining fed END-TO-END from a
    ray_tpu.data pipeline — block tasks generate/prepare token batches
    through the cluster runtime, ``iter_batches`` assembles them by
    column slicing with ``prefetch_blocks`` pulling ahead, and
    ``train.iter_device_batches`` overlaps ``jax.device_put`` of batch
    N+1 with step N.  Reports tokens/s plus the ``data_stall`` goodput
    share, against an UNPIPELINED baseline (same dataset, synchronous
    batch fetch + inline device_put) measured in the same run — the
    end-to-end proof that the input path feeds the train step with
    ~zero stall (north-star risk: host-side data plane eating MFU).
    """
    import numpy as np

    import ray_tpu
    from ray_tpu import data as rt_data
    from ray_tpu import train as rt_train

    cfg, on_tpu, state, optimizer, _loss_fn, step_fn = \
        _gpt2_bench_setup()
    batch, steps, n_blocks = (16, 20, 8) if on_tpu else (4, 12, 4)
    one_step = jax.jit(step_fn)
    rows_per_block = batch * steps // n_blocks
    seq = cfg.max_seq
    vocab = cfg.vocab_size

    def make_source(i):
        def src():
            rng = np.random.default_rng(1000 + i)
            return {"tokens": rng.integers(
                0, vocab, (rows_per_block, seq + 1), dtype=np.int64
            ).astype(np.int32)}
        return src

    owns = not ray_tpu.is_initialized()
    if owns:
        ray_tpu.init(mode="cluster", num_cpus=2)
    try:
        ds = rt_data.Dataset([make_source(i) for i in range(n_blocks)])

        ledger = _goodput.reset()
        warm = {"tokens": np.zeros((batch, seq + 1), np.int32)}
        with ledger.phase("compile"):
            s2, m = one_step(state, jax.device_put(warm))
            _ = jax.device_get(m["loss"])
        # Warm the CLUSTER too: one full untimed pass spawns workers,
        # ships the block-task code, and warms imports — otherwise the
        # first measured epoch (the unpipelined baseline) absorbs all
        # cold-start cost and the A/B comparison flatters the pipeline.
        for _ in ds.iter_batches(batch_size=batch, prefetch_blocks=0):
            pass

        def run_epoch(batches, *, inline_device_put: bool):
            """One pass over the dataset; returns (tokens/s, stall
            share of wall).  The final device_get inside the compute
            phase drains the async dispatch queue, so wall covers the
            real work."""
            st = state
            t0 = time.perf_counter()
            lg = _goodput.reset()
            n = 0
            it = iter(batches)
            last = None
            while True:
                if inline_device_put:
                    # Unpipelined baseline: the step loop itself waits
                    # for batch assembly + pays H2D inline.
                    try:
                        with rt_train.data_wait():
                            b = next(it)
                        b = jax.device_put(b)
                    except StopIteration:
                        break
                else:
                    try:
                        b = next(it)  # device batch; waits charged
                    except StopIteration:  # inside iter_device_batches
                        break
                with lg.phase("compute"):
                    st, last = one_step(st, b)
                n += 1
            with lg.phase("compute"):
                if last is not None:
                    _ = jax.device_get(last["loss"])
            wall = time.perf_counter() - t0
            stall = lg.snapshot()["seconds"].get("data_stall", 0.0)
            return (n * batch * seq / wall, stall / max(wall, 1e-9),
                    n)

        # Unpipelined baseline: synchronous fetch, no prefetch.
        base_tok_s, base_stall, n1 = run_epoch(
            ds.iter_batches(batch_size=batch, batch_format="numpy",
                            drop_last=True, prefetch_blocks=0),
            inline_device_put=True)
        # Zero-stall path: block prefetch + device prefetch.
        pipe_tok_s, pipe_stall, n2 = run_epoch(
            rt_train.iter_device_batches(
                ds.iter_batches(batch_size=batch,
                                batch_format="numpy",
                                drop_last=True, prefetch_blocks=2),
                depth=2),
            inline_device_put=False)
        if n1 != steps or n2 != steps:
            raise RuntimeError(
                f"pipeline delivered {n1}/{n2} batches, expected "
                f"{steps} — batching/split regression")
    finally:
        if owns:
            ray_tpu.shutdown()

    out = {
        "metric": "gpt2_data_pipeline_tokens_per_sec"
        + ("" if on_tpu else "_cpu"),
        "value": round(pipe_tok_s, 1),
        "unit": "tokens/s",
        # Pipelined throughput vs the unpipelined baseline of the SAME
        # run: >1.0 means the ingest pipeline pays for itself.
        "vs_baseline": round(pipe_tok_s / max(base_tok_s, 1e-9), 4),
        "extra": {
            "unpipelined_tokens_per_sec": round(base_tok_s, 1),
            "data_stall_share": round(pipe_stall, 4),
            "data_stall_share_unpipelined": round(base_stall, 4),
        },
    }
    print(json.dumps(out))
    _maybe_record(out, extra_rows=[
        {"benchmark": "data_pipeline_stall_share",
         "value": out["extra"]["data_stall_share"],
         "unit": "fraction", "higher_is_better": False}])


def long_context() -> None:
    """--long-context: ring attention (flash-fused, seq>=8k) vs the
    dense single-chip flash kernel (round-2 VERDICT item 3 'done' bar:
    ring within ~20% of dense flash).  vs_baseline = ring tokens/s /
    dense-flash tokens/s; one chip hosts the whole ring (n=1) — on a
    pod the seq axis spans chips and the ppermute rides ICI.
    """
    import os

    import functools

    import numpy as np
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from ray_tpu.ops.flash_attention import flash_attention
    from ray_tpu.parallel.ring_attention import ring_attention

    dev = jax.devices()
    on_tpu = dev[0].platform in ("tpu", "axon")
    if on_tpu:
        b, h, t, d = 2, 12, 8192, 64
        steps, reps = 8, 3
    else:
        b, h, t, d = 1, 2, 512, 32
        steps, reps = 2, 1

    key = jax.random.PRNGKey(0)
    qkv = jax.random.normal(key, (3, b, t, h, d), jnp.bfloat16)

    mesh = Mesh(np.array(dev), ("seq",))
    spec = P(None, "seq", None, None)
    ring = shard_map(functools.partial(ring_attention, causal=True),
                     mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)

    def bench_fn(attn):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2)

        grad = jax.grad(loss, argnums=(0, 1, 2))

        def run(q, k, v, n):
            def body(c, _):
                g = grad(q + c, k, v)
                return c + g[0][0, 0, 0, 0].astype(jnp.bfloat16), None
            c, _ = jax.lax.scan(body, jnp.bfloat16(0.0), None, length=n)
            return c

        runner = jax.jit(run, static_argnums=(3,))
        q, k, v = qkv
        _ = jax.device_get(runner(q, k, v, steps))  # warm-up/compile
        best = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            _ = jax.device_get(runner(q, k, v, steps))
            el = time.perf_counter() - t0
            best = max(best, b * t * steps / el)
        return best

    dense_tok_s = bench_fn(
        lambda q, k, v: flash_attention(q, k, v, causal=True))
    ring_tok_s = bench_fn(ring)
    # The ring mesh spans every local device while the dense baseline
    # jits onto one chip, so compare PER-CHIP throughput (and per-chip
    # MFU) — on an n-chip host the raw ring number is ~n× inflated.
    ring_tok_s_chip = ring_tok_s / len(dev)

    # Causal fwd+bwd attention FLOPs per token (QK^T + PV, backward
    # ~2.5x forward, causal halves the visible area).
    flops_tok = 3.5 * (4 * h * t * d) * 0.5
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = _PEAK_FLOPS.get(gen, _PEAK_FLOPS["v5e"])
    mfu = ring_tok_s_chip * flops_tok / peak if on_tpu else 0.0
    out = {
        "metric": f"ring_attention_seq{t}_tokens_per_sec_per_chip"
        + ("" if on_tpu else "_cpu"),
        "value": round(ring_tok_s_chip, 1),
        "unit": "tokens/s",
        "vs_baseline": round(ring_tok_s_chip / dense_tok_s, 4),
        "extra": {"dense_flash_tokens_per_sec": round(dense_tok_s, 1),
                  "ring_devices": len(dev),
                  "ring_attention_mfu": round(mfu, 4)},
    }
    print(json.dumps(out))
    _maybe_record(out)


def cold_start() -> None:
    """--cold-start: 100-replica serve deployment cold start through
    the control-plane fast path — the warm-worker prestart pool is
    filled FIRST, then the wall time from ``serve.run`` to every
    replica answering is measured.  Reports the adoption vs cold-spawn
    delta alongside (a nonzero fallback count means the pool was
    outrun and some replicas paid a full interpreter spawn).
    """
    import os
    import sys

    n_replicas = 10 if "--quick" in sys.argv else 100
    # Pool sizing must precede init so the agent's config carries it
    # (+ headroom for the serve controller/proxy actors).
    os.environ.setdefault("RT_WORKER_PRESTART", str(n_replicas + 8))
    os.environ.setdefault("RT_WORKER_POOL_MAX_WORKERS",
                          str(n_replicas + 64))
    os.environ.setdefault("RT_WORKER_PRESTART_BURST", "16")
    os.environ.setdefault("RT_ACTOR_READY_TIMEOUT_S", "600")

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util.scale_bench import _pool_totals, wait_pool_fill

    ray_tpu.init(mode="cluster", num_cpus=4)
    try:
        filled = wait_pool_fill(n_replicas + 4, timeout=600.0)
        print(f"prestart pool warm: {filled} idle worker(s)",
              flush=True)
        before = _pool_totals()

        @serve.deployment(num_replicas=n_replicas, name="cold",
                          ray_actor_options={"num_cpus": 0})
        def noop(_req=None):
            return "ok"

        t0 = time.perf_counter()
        serve.run(noop.bind(), route_prefix="/cold")
        # "Cold start" ends when every replica process answers — poll
        # each replica actor directly (the handle would be satisfied
        # by the first few live replicas).
        ctl = ray_tpu.get_actor(serve.CONTROLLER_NAME)
        replicas = ray_tpu.get(ctl.get_replicas.remote("cold"),
                               timeout=120)
        ray_tpu.get([r.ongoing.remote() for r in replicas],
                    timeout=600)
        dt = time.perf_counter() - t0
        after = _pool_totals()
        adopted = int(after["adoptions"] - before["adoptions"])
        cold = int(after["cold_spawns"] - before["cold_spawns"])
        out = {
            "metric": f"serve_cold_start_{n_replicas}_replicas_s",
            "value": round(dt, 3), "unit": "s",
            "extra": {"replicas": len(replicas), "adopted": adopted,
                      "cold_spawn_fallbacks": cold},
        }
        print(json.dumps(out))
        if len(replicas) != n_replicas:
            raise RuntimeError(
                f"cold start brought up {len(replicas)} of "
                f"{n_replicas} replicas")
        _maybe_record(out, higher_is_better=False)
    finally:
        ray_tpu.shutdown()


def serve_llm() -> None:
    """--serve-llm: load-test the LLM inference plane at saturating
    concurrency — a tiny GPT-2 ``LLMDeployment`` (continuous-batching
    engine + paged KV cache) behind serve, token streams pulled by
    concurrent clients through ``handle.stream``.  Reports p50/p99
    time-to-first-token and aggregate generated tokens/s, plus honest
    decode MFU via ``decode_flops_per_token`` (the 6ND training count
    would overstate it 3x); 0 off-TPU.  ``--record`` appends
    serve_llm_tokens_per_sec (floored in PERF.jsonl) and the TTFT
    percentiles."""
    import dataclasses
    import sys
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import EngineConfig, llm_deployment
    from ray_tpu.models.gpt2 import GPT2Config

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    quick = "--quick" in sys.argv
    if on_tpu:
        cfg = GPT2Config(n_layer=12, n_head=12, d_model=768, d_ff=3072,
                         vocab_size=50257, max_seq=1024, remat=False)
    else:
        cfg = GPT2Config(vocab_size=512, n_layer=2, n_head=4,
                         d_model=128, d_ff=512, max_seq=256,
                         remat=False, dtype=jnp.float32)
    cfg = dataclasses.replace(cfg, attn_impl="dense")
    engine_cfg = EngineConfig(page_size=16, num_pages=256, max_batch=8,
                              prefill_token_budget=512)
    concurrency = 8                      # = max_batch: saturates the
    per_client = 1 if quick else 4       # continuous batch
    prompt_len, max_tokens = 16, 32
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(concurrency * per_client)]

    ray_tpu.init(mode="cluster", num_cpus=4)
    try:
        handle = serve.run(
            llm_deployment(name="llm", model="gpt2", model_cfg=cfg,
                           engine_cfg=engine_cfg),
            route_prefix="/llm")
        # Warm the full path (replica __init__ already compiled the
        # engine; this warms the handle/stream plumbing and the
        # pad-16 prefill shape).  "warmup" keeps its compile-laden
        # prefill out of the engine's TTFT/TPOT accounting.
        _ = [f for f in handle.stream(
            {"prompt": prompts[0], "max_tokens": 4,
             "warmup": True})]

        ttfts, counts, errors, tpots = [], [], [], []
        lock = threading.Lock()

        def client(idx: int) -> None:
            from ray_tpu.util import tracing

            for r in range(per_client):
                payload = {"prompt": prompts[idx * per_client + r],
                           "max_tokens": max_tokens}
                t0 = time.perf_counter()
                first, n, prev = None, 0, None
                gaps = []
                try:
                    # request_id on: the run measures throughput WITH
                    # request tracing active (waiting/prefill/decode
                    # spans + TPOT), so the recorded tokens/s floor
                    # bounds the tracing overhead.
                    for fr in handle.stream(
                            payload,
                            request_id=tracing.new_request_id()):
                        if "error" in fr:
                            raise RuntimeError(fr["error"])
                        if "token" in fr:
                            now = time.perf_counter()
                            if first is None:
                                first = now - t0
                            elif prev is not None:
                                gaps.append(now - prev)
                            prev = now
                            n += 1
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                    continue
                with lock:
                    ttfts.append(first)
                    counts.append(n)
                    tpots.extend(gaps)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        if errors:
            raise RuntimeError(
                f"{len(errors)} request(s) failed: {errors[:3]}")
        stats = ray_tpu.get(handle.method("stats").remote(), timeout=30)
    finally:
        ray_tpu.shutdown()

    tok_s = sum(counts) / wall
    ttft_ms = np.asarray(sorted(ttfts)) * 1e3
    p50 = float(np.percentile(ttft_ms, 50))
    p99 = float(np.percentile(ttft_ms, 99))
    tpot_ms = np.asarray(sorted(tpots)) * 1e3 if tpots else \
        np.asarray([0.0])
    tpot_p50 = float(np.percentile(tpot_ms, 50))
    tpot_p99 = float(np.percentile(tpot_ms, 99))
    # TTFT phase decomposition from the engine's own accounting:
    # where the mean first token actually waited.
    n_req = max(stats.get("ttft_requests", 0), 1)
    wait_ms = 1e3 * stats.get("ttft_waiting_s_total", 0.0) / n_req
    prefill_ms = 1e3 * stats.get("ttft_prefill_s_total", 0.0) / n_req
    print(f"ttft decomposition (engine means over "
          f"{stats.get('ttft_requests', 0)} request(s)): "
          f"engine_waiting {wait_ms:.1f}ms + prefill "
          f"{prefill_ms:.1f}ms of ttft p50 {p50:.1f}ms; "
          f"tpot p50 {tpot_p50:.2f}ms p99 {tpot_p99:.2f}ms")
    import os

    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = _PEAK_FLOPS.get(gen, _PEAK_FLOPS["v5e"])
    mfu = (tok_s * cfg.decode_flops_per_token(prompt_len + max_tokens // 2)
           / peak) if on_tpu else 0.0
    out = {
        "metric": "serve_llm_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu, 4),   # decode MFU (0 off-TPU)
        "extra": {
            "ttft_p50_ms": round(p50, 1),
            "ttft_p99_ms": round(p99, 1),
            "tpot_p50_ms": round(tpot_p50, 2),
            "tpot_p99_ms": round(tpot_p99, 2),
            "ttft_engine_waiting_mean_ms": round(wait_ms, 2),
            "ttft_prefill_mean_ms": round(prefill_ms, 2),
            "requests": len(counts),
            "concurrency": concurrency,
            "kv_pages_used_after": stats["kv_pages_used"],
            "engine_steps": stats["steps"],
            "evictions": stats["evictions"],
        },
    }
    print(json.dumps(out))
    _maybe_record(out, extra_rows=[
        {"benchmark": "serve_llm_ttft_p50_ms", "value": round(p50, 1),
         "unit": "ms", "higher_is_better": False},
        {"benchmark": "serve_llm_ttft_p99_ms", "value": round(p99, 1),
         "unit": "ms", "higher_is_better": False},
        {"benchmark": "serve_llm_tpot_p99_ms",
         "value": round(tpot_p99, 2),
         "unit": "ms", "higher_is_better": False}])


def fsdp() -> None:
    """--fsdp: GPT-2 sharded train steps over a 2-process CPU mesh.

    The multi-host training plane's standing bench: two member
    processes (each with 2 virtual CPU devices) rendezvous through
    jax.distributed, lay the 4 devices out as a process-contiguous
    fsdp x tensor gang mesh (train.distributed), shard the TrainState
    by the GPT-2 partition rules, and run jit-with-shardings train
    steps whose gradient reductions cross the process boundary (gloo).
    Records ``train_fsdp_tokens_per_sec`` (global tokens through the
    sharded step, a floor against GSPMD-path regressions — extra
    resharding copies, lost donation) plus per-mesh-axis collective
    byte shares harvested by util/xprof from the timed executable's
    post-SPMD HLO.  An MFU row rides along only on real accelerators;
    on the CPU gang that ratio measures nothing and is omitted."""
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--fsdp-member",
         str(rank), addr], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for rank in range(2)]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for rank, (p, o) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"fsdp bench member {rank} failed:\n{o[-3000:]}")
    member = None
    for line in outs[0].splitlines():
        if line.startswith("FSDP-MEMBER-0 "):
            member = json.loads(line.split(" ", 1)[1])
    if member is None:
        raise RuntimeError(
            f"fsdp bench member 0 printed no result:\n{outs[0][-3000:]}")
    on_accel = member.get("platform") in ("tpu", "axon")
    out = {
        "metric": "train_fsdp_tokens_per_sec",
        "value": round(member["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,  # CPU mesh: MFU vs 35% is not meaningful
        "mesh": member["mesh"],
        "world": 2,
        "compile_s": round(member["compile_s"], 2),
        "platform": member.get("platform", "cpu"),
        "collective_bytes": member.get("collective_bytes", 0.0),
        "axis_shares": member.get("axis_shares", {}),
    }
    # MFU against a TPU peak measures nothing on a CPU gang — keep
    # the key (and its ledger row) only on real accelerators.
    if on_accel:
        out["mfu"] = member["mfu"]
    print(json.dumps(out))
    # Axis byte shares are static facts of the compiled program; a
    # rising fsdp/tensor share means the partitioner started moving
    # more bytes over that axis per step (lower is better).
    rows = [
        {"benchmark": f"train_fsdp_collective_share_{axis}",
         "value": share, "unit": "fraction", "higher_is_better": False}
        for axis, share in sorted(member.get("axis_shares",
                                             {}).items())]
    if on_accel:
        rows.append({"benchmark": "train_fsdp_mfu",
                     "value": member["mfu"], "unit": "fraction",
                     "higher_is_better": True})
    _maybe_record(out, extra_rows=rows)


def _fsdp_member(rank: int, addr: str) -> None:
    """One rank of the --fsdp bench (spawned by ``fsdp`` above)."""
    import os
    import time as _time

    import numpy as np

    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=2, process_id=rank)
    from jax.sharding import NamedSharding, PartitionSpec

    from ray_tpu.models.gpt2 import (GPT2Config, gpt2_init,
                                     gpt2_loss_fn)
    from ray_tpu.parallel.mesh import gang_mesh
    from ray_tpu.parallel.partition_rules import tree_shardings
    from ray_tpu.train import distributed as dist
    from ray_tpu.train.train_step import (TrainState, make_optimizer,
                                          make_sharded_train_step)

    cfg = GPT2Config(vocab_size=2048, n_layer=4, n_head=8, d_model=256,
                     d_ff=1024, max_seq=256, remat=True)
    params = gpt2_init(cfg, jax.random.PRNGKey(0))
    optimizer = make_optimizer(total_steps=1000)
    state = TrainState.create(params, optimizer)
    shape = dist.derive_mesh_shape(2, jax.local_device_count())
    mesh = gang_mesh(shape)
    state, specs = dist.shard_train_state(
        state, mesh, dist.rules_for_model("gpt2"))
    shardings = tree_shardings(mesh, specs)
    # telemetry=True: the step compiles through the AOT path, so the
    # xprof plane harvests the post-SPMD HLO — per-axis collective
    # bytes come from the SAME executable the bench times.
    step = make_sharded_train_step(
        lambda p, b: gpt2_loss_fn(cfg, p, b, loss_chunk=0), optimizer,
        mesh=mesh, state_shardings=shardings,
        batch_sharding=NamedSharding(mesh, PartitionSpec("fsdp")),
        telemetry=True)
    gbs, steps = 8, 6
    rng = np.random.default_rng(0)
    full = rng.integers(0, cfg.vocab_size,
                        (gbs, cfg.max_seq + 1)).astype(np.int32)
    lo, hi = dist.global_batch_slice(gbs, shape, rank, 2)
    batch = dist.put_global_batch({"tokens": full[lo:hi]}, mesh,
                                  global_batch_size=gbs)
    t0 = _time.perf_counter()
    state, metrics = step(state, batch)
    _ = dist.metrics_to_host(metrics)
    compile_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    for _i in range(steps):
        state, metrics = step(state, batch)
    _ = dist.metrics_to_host(metrics)  # sync the async dispatch tail
    elapsed = _time.perf_counter() - t0
    tok_s = gbs * cfg.max_seq * steps / elapsed
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = _PEAK_FLOPS.get(gen, _PEAK_FLOPS["v5e"]) * len(jax.devices())
    mfu = tok_s * cfg.flops_per_token() / peak
    # Per-axis collective byte shares from the xprof plane: static
    # post-SPMD HLO facts of the timed executable (deterministic per
    # compile — unlike timing, safe to regression-guard).
    from ray_tpu.util import xprof

    colls = (xprof.local_programs().get("train_step") or {}).get(
        "collectives") or {}
    total_cbytes = sum(a.get("bytes", 0.0) for a in colls.values())
    axis_shares = {
        axis: round(a.get("bytes", 0.0) / total_cbytes, 4)
        for axis, a in colls.items()} if total_cbytes > 0 else {}
    if rank == 0:
        print("FSDP-MEMBER-0 " + json.dumps(
            {"tokens_per_sec": tok_s, "compile_s": compile_s,
             "mesh": shape, "mfu": round(mfu, 6),
             "platform": jax.devices()[0].platform,
             "collective_bytes": total_cbytes,
             "axis_shares": axis_shares,
             "loss": dist.metrics_to_host(metrics)["loss"]}),
            flush=True)


def _maybe_record(out: dict, extra_rows: list = None,
                  higher_is_better: bool = True) -> None:
    """--record: append to the PERF.jsonl round-over-round regression
    ledger (tests/test_perf_ledger.py guards >20% drops)."""
    import sys

    if "--record" not in sys.argv:
        return
    from ray_tpu.util import perf_ledger

    perf_ledger.record(
        [{"benchmark": out["metric"], "value": out["value"],
          "unit": out["unit"],
          "higher_is_better": higher_is_better}]
        + list(extra_rows or []),
        source="bench")


if __name__ == "__main__":
    import sys

    if "--long-context" in sys.argv:
        long_context()
    elif "--data-pipeline" in sys.argv:
        data_pipeline()
    elif "--cold-start" in sys.argv:
        cold_start()
    elif "--serve-llm" in sys.argv:
        serve_llm()
    elif "--fsdp-member" in sys.argv:
        i = sys.argv.index("--fsdp-member")
        _fsdp_member(int(sys.argv[i + 1]), sys.argv[i + 2])
    elif "--fsdp" in sys.argv:
        fsdp()
    else:
        main()
