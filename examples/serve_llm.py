"""Serve a tiny GPT-2 through the LLM inference plane and stream
tokens — over the deployment handle and over HTTP (chunked ndjson).

Run:  JAX_PLATFORMS=cpu python examples/serve_llm.py

The deployment hosts one continuous-batching GenerationEngine per
replica (paged KV cache, step-granularity admission); requests carry
token-id prompts and sampling parameters, responses stream one frame
per token.  Autoscaling: pass serve.AutoscalingConfig to
``llm_deployment(autoscaling=...)`` and replica count follows queue
depth + streams in flight.  See README "LLM serving".
"""

import dataclasses
import json
import urllib.request

import jax.numpy as jnp

import ray_tpu
from ray_tpu import serve
from ray_tpu.llm import EngineConfig, llm_deployment
from ray_tpu.models.gpt2 import GPT2Config


def main() -> None:
    cfg = dataclasses.replace(GPT2Config.tiny(), remat=False,
                              dtype=jnp.float32)
    ray_tpu.init(mode="cluster", num_cpus=4)
    try:
        handle = serve.run(
            llm_deployment(
                name="llm", model="gpt2", model_cfg=cfg,
                engine_cfg=EngineConfig(page_size=16, num_pages=128,
                                        max_batch=8)),
            route_prefix="/llm")

        # --- stream over the handle (in-cluster clients)
        print("handle stream:")
        for frame in handle.stream({"prompt": [5, 9, 101],
                                    "max_tokens": 8,
                                    "temperature": 0.8, "top_k": 40,
                                    "seed": 7}):
            print("  ", frame)

        # --- stream over HTTP (chunked ndjson; curl-able)
        port = serve.start_http_proxy()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/llm",
            data=json.dumps({"prompt": [5, 9, 101],
                             "max_tokens": 5}).encode(),
            headers={"Content-Type": "application/json"})
        print(f"http stream (port {port}):")
        with urllib.request.urlopen(req, timeout=120) as resp:
            for line in resp:
                print("  ", line.decode().rstrip())

        print("engine stats:",
              ray_tpu.get(handle.method("stats").remote()))
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
