// Shared-memory object pool: the native data plane of the node store.
//
// Role-equivalent to the reference's plasma store core (ref:
// src/ray/object_manager/plasma/ — ObjectStore over a dlmalloc slab with
// an object table), redesigned for the one-agent-per-TPU-host layout:
// ONE POSIX shm region holds a header + object index + data slab, and
// every process on the host (agent, workers, driver) attaches the same
// region.  Unlike the per-object-segment Python backend, creating an
// object is a lock + free-list carve — no shm_open/ftruncate syscall per
// object, no fd churn, and lookups are an open-addressed hash probe in
// shared memory.
//
// Concurrency: a process-shared robust pthread mutex guards the index
// and allocator (EOWNERDEAD is recovered with pthread_mutex_consistent,
// so a SIGKILLed worker cannot wedge the host).  Object payloads are
// written outside the lock: an object becomes visible to lookups only
// when sealed, and objects are immutable after seal — the same
// create/seal protocol as plasma.
//
// Allocator: address-ordered first-fit free list with split on carve and
// coalesce on free.  O(free blocks) per alloc/free; the node store holds
// thousands of objects, not millions, and the lock already serializes.

#include <cstdint>
#include <cstring>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <fcntl.h>
#include <unistd.h>
#include <errno.h>

namespace {

constexpr uint64_t kMagic = 0x52545055504f4f4cULL;  // "RTPUPOOL"
constexpr uint32_t kEmpty = 0;
constexpr uint32_t kAllocated = 1;
constexpr uint32_t kSealed = 2;
constexpr uint32_t kTombstone = 3;
constexpr uint32_t kPendingDelete = 4;

struct Slot {
  uint8_t key[16];
  uint64_t off;       // data offset from slab base
  uint64_t size;
  uint32_t state;
  uint32_t pins;      // cross-process read pins; free deferred while >0
};

struct FreeBlock {
  uint64_t size;      // bytes of this free block (incl. header)
  uint64_t next;      // offset of next free block, ~0ull = none
};

constexpr uint64_t kNone = ~0ull;
constexpr uint64_t kAlign = 64;

struct PoolHeader {
  uint64_t magic;
  uint64_t total_bytes;     // whole mapping
  uint64_t slab_off;        // data slab start
  uint64_t slab_bytes;
  uint64_t table_off;
  uint64_t table_slots;
  uint64_t free_head;       // offset into slab of first free block
  uint64_t used_bytes;
  uint64_t n_objects;
  pthread_mutex_t mutex;
};

struct Pool {
  int fd;
  uint8_t* base;
  uint64_t map_bytes;
  PoolHeader* hdr;
};

inline Slot* table(Pool* p) {
  return reinterpret_cast<Slot*>(p->base + p->hdr->table_off);
}

inline uint64_t hash_key(const uint8_t* key) {
  uint64_t h = 1469598103934665603ULL;  // FNV-1a over the 16-byte id
  for (int i = 0; i < 16; i++) { h ^= key[i]; h *= 1099511628211ULL; }
  return h;
}

int lock(Pool* p) {
  int rc = pthread_mutex_lock(&p->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // Holder died mid-critical-section.  Index/allocator mutations are
    // small pointer swings; make the mutex usable again and continue —
    // the worst case is a leaked block, never a corrupted reader.
    pthread_mutex_consistent(&p->hdr->mutex);
    rc = 0;
  }
  return rc;
}

void unlock(Pool* p) { pthread_mutex_unlock(&p->hdr->mutex); }

Slot* find_slot(Pool* p, const uint8_t* key, bool for_insert) {
  Slot* t = table(p);
  uint64_t n = p->hdr->table_slots;
  uint64_t i = hash_key(key) % n;
  Slot* first_tomb = nullptr;
  for (uint64_t probe = 0; probe < n; probe++, i = (i + 1) % n) {
    Slot* s = &t[i];
    if (s->state == kEmpty)
      return for_insert ? (first_tomb ? first_tomb : s) : nullptr;
    if (s->state == kTombstone) {
      if (for_insert && !first_tomb) first_tomb = s;
      continue;
    }
    if (memcmp(s->key, key, 16) == 0) return s;
  }
  return for_insert ? first_tomb : nullptr;
}

uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

}  // namespace

extern "C" {

// Create (or open existing) pool; returns opaque handle or null.
void* rt_pool_create(const char* name, uint64_t slab_bytes,
                     uint64_t table_slots) {
  uint64_t table_bytes = align_up(table_slots * sizeof(Slot));
  uint64_t hdr_bytes = align_up(sizeof(PoolHeader));
  uint64_t total = hdr_bytes + table_bytes + slab_bytes;

  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  bool created = fd >= 0;
  if (!created) {
    if (errno != EEXIST) return nullptr;
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
  } else if (ftruncate(fd, (off_t)total) != 0) {
    close(fd); shm_unlink(name); return nullptr;
  }
  if (!created) {
    struct stat st;
    if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
    total = (uint64_t)st.st_size;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }

  Pool* p = new Pool{fd, (uint8_t*)mem, total, (PoolHeader*)mem};
  if (created) {
    PoolHeader* h = p->hdr;
    memset(h, 0, sizeof(PoolHeader));
    h->total_bytes = total;
    h->slab_off = hdr_bytes + table_bytes;
    h->slab_bytes = slab_bytes;
    h->table_off = hdr_bytes;
    h->table_slots = table_slots;
    memset(p->base + h->table_off, 0, table_bytes);
    FreeBlock* fb = (FreeBlock*)(p->base + h->slab_off);
    fb->size = slab_bytes;
    fb->next = kNone;
    h->free_head = 0;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
    __sync_synchronize();
    h->magic = kMagic;
  } else {
    // Spin briefly until the creator publishes the magic.
    for (int i = 0; i < 100000 && p->hdr->magic != kMagic; i++)
      usleep(10);
    if (p->hdr->magic != kMagic) {
      munmap(mem, total); close(fd); delete p; return nullptr;
    }
  }
  return p;
}

void* rt_pool_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, (size_t)st.st_size,
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  Pool* p = new Pool{fd, (uint8_t*)mem, (uint64_t)st.st_size,
                     (PoolHeader*)mem};
  if (p->hdr->magic != kMagic) {
    munmap(mem, (size_t)st.st_size); close(fd); delete p;
    return nullptr;
  }
  return p;
}

// Reserve space for an object; returns ABSOLUTE offset of its payload
// within the mapping, or ~0 on full/duplicate.
uint64_t rt_pool_alloc(void* pool, const uint8_t* key, uint64_t size) {
  Pool* p = (Pool*)pool;
  uint64_t need = align_up(size + sizeof(uint64_t));  // size header
  if (lock(p) != 0) return kNone;
  Slot* existing = find_slot(p, key, false);
  if (existing && existing->state != kTombstone) { unlock(p); return kNone; }
  // First-fit scan.
  uint64_t prev = kNone, cur = p->hdr->free_head;
  uint8_t* slab = p->base + p->hdr->slab_off;
  while (cur != kNone) {
    FreeBlock* fb = (FreeBlock*)(slab + cur);
    if (fb->size >= need) break;
    prev = cur; cur = fb->next;
  }
  if (cur == kNone) { unlock(p); return kNone; }
  FreeBlock* fb = (FreeBlock*)(slab + cur);
  uint64_t remain = fb->size - need;
  uint64_t next = fb->next;
  if (remain >= sizeof(FreeBlock) + kAlign) {
    FreeBlock* rest = (FreeBlock*)(slab + cur + need);
    rest->size = remain;
    rest->next = next;
    next = cur + need;
  } else {
    need = fb->size;  // absorb the sliver
  }
  if (prev == kNone) p->hdr->free_head = next;
  else ((FreeBlock*)(slab + prev))->next = next;

  *(uint64_t*)(slab + cur) = need;  // block size header
  Slot* s = find_slot(p, key, true);
  if (!s) {  // table full: give the block back
    FreeBlock* back = (FreeBlock*)(slab + cur);
    back->size = need; back->next = p->hdr->free_head;
    p->hdr->free_head = cur;
    unlock(p);
    return kNone;
  }
  memcpy(s->key, key, 16);
  s->off = cur + sizeof(uint64_t);
  s->size = size;
  s->state = kAllocated;
  s->pins = 0;
  p->hdr->used_bytes += need;
  p->hdr->n_objects += 1;
  unlock(p);
  return p->hdr->slab_off + cur + sizeof(uint64_t);
}

int rt_pool_seal(void* pool, const uint8_t* key) {
  Pool* p = (Pool*)pool;
  if (lock(p) != 0) return -1;
  Slot* s = find_slot(p, key, false);
  int rc = -1;
  if (s && s->state == kAllocated) { s->state = kSealed; rc = 0; }
  unlock(p);
  return rc;
}

// Absolute payload offset + size of a SEALED object; ~0 if absent.
uint64_t rt_pool_lookup(void* pool, const uint8_t* key, uint64_t* size) {
  Pool* p = (Pool*)pool;
  if (lock(p) != 0) return kNone;
  Slot* s = find_slot(p, key, false);
  uint64_t off = kNone;
  if (s && s->state == kSealed) { off = p->hdr->slab_off + s->off; *size = s->size; }
  unlock(p);
  return off;
}

namespace {
void free_block_locked(Pool* p, Slot* s);
void clear_tombstones_locked(Pool* p, Slot* s);
}

int rt_pool_delete(void* pool, const uint8_t* key) {
  Pool* p = (Pool*)pool;
  if (lock(p) != 0) return -1;
  Slot* s = find_slot(p, key, false);
  if (!s || s->state == kTombstone || s->state == kEmpty) {
    unlock(p); return -1;
  }
  if (s->state == kAllocated) {
    // A writer is (or was) mid-copy into this block: freeing it would
    // let the bytes be recycled under the write.  Refuse; a crashed
    // writer leaks one block, which is the safe failure.
    unlock(p); return -2;
  }
  if (s->pins > 0) {
    // Readers hold the payload: defer the free to the last unpin.
    s->state = kPendingDelete;
    unlock(p); return 0;
  }
  free_block_locked(p, s);
  unlock(p);
  return 0;
}

namespace {
void free_block_locked(Pool* p, Slot* s) {
  uint8_t* slab = p->base + p->hdr->slab_off;
  uint64_t blk = s->off - sizeof(uint64_t);
  uint64_t bsize = *(uint64_t*)(slab + blk);
  // Address-ordered insert with neighbor coalescing.
  uint64_t prev = kNone, cur = p->hdr->free_head;
  while (cur != kNone && cur < blk) {
    prev = cur; cur = ((FreeBlock*)(slab + cur))->next;
  }
  FreeBlock* nb = (FreeBlock*)(slab + blk);
  nb->size = bsize;
  nb->next = cur;
  if (prev == kNone) p->hdr->free_head = blk;
  else ((FreeBlock*)(slab + prev))->next = blk;
  // Coalesce with next.
  if (cur != kNone && blk + nb->size == cur) {
    FreeBlock* cb = (FreeBlock*)(slab + cur);
    nb->size += cb->size;
    nb->next = cb->next;
  }
  // Coalesce with prev.
  if (prev != kNone) {
    FreeBlock* pb = (FreeBlock*)(slab + prev);
    if (prev + pb->size == blk) {
      pb->size += nb->size;
      pb->next = nb->next;
    }
  }
  p->hdr->used_bytes -= bsize;
  p->hdr->n_objects -= 1;
  s->state = kTombstone;
  s->pins = 0;
  clear_tombstones_locked(p, s);
}

// If the probe chain ends right after this slot, convert the trailing
// run of tombstones back to empty — keeps miss lookups O(chain), not
// O(table), under sustained churn.
void clear_tombstones_locked(Pool* p, Slot* s) {
  Slot* t = table(p);
  uint64_t n = p->hdr->table_slots;
  uint64_t i = (uint64_t)(s - t);
  if (t[(i + 1) % n].state != kEmpty) return;
  while (t[i].state == kTombstone) {
    t[i].state = kEmpty;
    i = (i + n - 1) % n;
  }
}
}  // namespace

// Lookup AND pin in one critical section; the payload cannot be freed
// until rt_pool_unpin.  Returns the absolute offset or ~0.
uint64_t rt_pool_pin(void* pool, const uint8_t* key, uint64_t* size) {
  Pool* p = (Pool*)pool;
  if (lock(p) != 0) return kNone;
  Slot* s = find_slot(p, key, false);
  uint64_t off = kNone;
  if (s && s->state == kSealed) {
    s->pins += 1;
    off = p->hdr->slab_off + s->off;
    *size = s->size;
  }
  unlock(p);
  return off;
}

int rt_pool_unpin(void* pool, const uint8_t* key) {
  Pool* p = (Pool*)pool;
  if (lock(p) != 0) return -1;
  Slot* s = find_slot(p, key, false);
  int rc = -1;
  if (s && (s->state == kSealed || s->state == kPendingDelete) &&
      s->pins > 0) {
    s->pins -= 1;
    rc = 0;
    if (s->pins == 0 && s->state == kPendingDelete)
      free_block_locked(p, s);
  }
  unlock(p);
  return rc;
}

int rt_pool_contains(void* pool, const uint8_t* key) {
  Pool* p = (Pool*)pool;
  if (lock(p) != 0) return 0;
  Slot* s = find_slot(p, key, false);
  int rc = (s && s->state == kSealed) ? 1 : 0;
  unlock(p);
  return rc;
}

void rt_pool_stats(void* pool, uint64_t* used, uint64_t* capacity,
                   uint64_t* n_objects) {
  Pool* p = (Pool*)pool;
  if (lock(p) != 0) { *used = *capacity = *n_objects = 0; return; }
  *used = p->hdr->used_bytes;
  *capacity = p->hdr->slab_bytes;
  *n_objects = p->hdr->n_objects;
  unlock(p);
}

void rt_pool_close(void* pool) {
  Pool* p = (Pool*)pool;
  munmap(p->base, p->map_bytes);
  close(p->fd);
  delete p;
}

int rt_pool_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
