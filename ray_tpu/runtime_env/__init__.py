"""Per-task/actor runtime environments.

Role-equivalent to the reference's runtime_env subsystem (ref:
python/ray/_private/runtime_env/ — plugins working_dir.py, py_modules.py,
packaging.py; applied by the raylet's worker pool keyed by env hash,
worker_pool.h:216).  Redesigned host-native: packages are content-
addressed zips in the controller KV (the cluster's metadata plane), so a
TPU-pod worker fetches them over the same control connection it already
has — no external storage, no per-node agent daemon.

Supported fields:
- ``env_vars``:   dict of environment variables set in the worker process
                  before any user code runs.
- ``working_dir``: local directory, packaged at first use and materialized
                  as the worker's cwd (also on sys.path, matching the
                  reference).
- ``py_modules``: list of local package directories, each importable in
                  the worker.
- ``pip``:        requirement list (or {"packages": [...]}); the worker
                  process starts inside a hash-keyed cached virtualenv
                  with those requirements installed (pip.py).

Workers are cached per environment hash: tasks with the same runtime env
reuse warm workers; a different env gets a fresh process (ref:
worker_pool.h PopWorker runtime_env_hash matching).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile
from typing import Any, Dict, List, Optional, Tuple

_PKG_PREFIX = "runtime_env/pkg/"
_MAX_PKG_BYTES = 256 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def normalize(runtime_env: Optional[Dict[str, Any]]
              ) -> Optional[Dict[str, Any]]:
    """Validate + canonicalize a user-supplied runtime_env dict."""
    if not runtime_env:
        return None
    allowed = {"env_vars", "working_dir", "py_modules", "pip", "uv"}
    unknown = set(runtime_env) - allowed
    if unknown:
        raise ValueError(
            f"Unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(allowed)}")
    out: Dict[str, Any] = {}
    env_vars = runtime_env.get("env_vars") or {}
    if env_vars:
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in env_vars.items()):
            raise TypeError("runtime_env['env_vars'] must be Dict[str, str]")
        reserved = [k for k in env_vars if k.startswith("RT_")]
        if reserved:
            raise ValueError(
                f"runtime_env env_vars {reserved} use the reserved RT_ "
                f"prefix (framework control variables)")
        out["env_vars"] = dict(sorted(env_vars.items()))
    wd = runtime_env.get("working_dir")
    if wd:
        wd = os.path.abspath(os.path.expanduser(wd))
        if not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        out["working_dir"] = wd
    if runtime_env.get("pip") and runtime_env.get("uv"):
        raise ValueError(
            "runtime_env cannot set both 'pip' and 'uv' — pick one "
            "installer for the env (ref: runtime_env plugin "
            "exclusivity in _private/runtime_env/uv.py)")
    if runtime_env.get("pip"):
        from .pip import normalize_pip

        out["pip"] = normalize_pip(runtime_env["pip"])
    if runtime_env.get("uv"):
        from .uv import normalize_uv

        out["uv"] = normalize_uv(runtime_env["uv"])
    mods = runtime_env.get("py_modules") or []
    if mods:
        norm = []
        for m in mods:
            m = os.path.abspath(os.path.expanduser(m))
            if not os.path.isdir(m):
                raise ValueError(f"py_modules entry {m!r} is not a "
                                 f"directory")
            norm.append(m)
        out["py_modules"] = norm
    return out or None


def _zip_dir(root: str) -> bytes:
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in _EXCLUDE_DIRS]
            for fn in sorted(filenames):
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                try:
                    total += os.path.getsize(full)
                except OSError:
                    continue
                if total > _MAX_PKG_BYTES:
                    raise ValueError(
                        f"runtime_env package {root!r} exceeds "
                        f"{_MAX_PKG_BYTES >> 20} MiB")
                zf.write(full, rel)
    return buf.getvalue()


def package(env: Dict[str, Any]
            ) -> Tuple[Dict[str, Any], Dict[str, bytes]]:
    """Driver side: build the wire spec + content-addressed blobs.

    Pure (no IO beyond reading the dirs): returns (spec, {kv_key: zip
    bytes}).  The caller uploads any blob whose key is not yet in the
    controller KV; the spec (hashes + env_vars only) travels in the
    TaskSpec.
    """
    blobs: Dict[str, bytes] = {}

    def pack(path: str) -> str:
        data = _zip_dir(path)
        digest = hashlib.sha256(data).hexdigest()[:32]
        blobs[_PKG_PREFIX + digest] = data
        return digest

    spec: Dict[str, Any] = {}
    if env.get("env_vars"):
        spec["env_vars"] = env["env_vars"]
    if env.get("pip"):
        # Requirements travel in the spec (tiny); the venv builds on
        # each node at first use, cached by requirement hash.
        spec["pip"] = list(env["pip"])
    if env.get("uv"):
        spec["uv"] = list(env["uv"])
    if env.get("working_dir"):
        spec["working_dir_pkg"] = pack(env["working_dir"])
    if env.get("py_modules"):
        spec["py_modules_pkgs"] = [
            {"name": os.path.basename(m.rstrip(os.sep)),
             "pkg": pack(m)} for m in env["py_modules"]]
    spec["hash"] = env_hash(spec)
    return spec, blobs


def env_hash(spec: Optional[Dict[str, Any]]) -> str:
    """Stable identity of a packaged spec — the worker-pool cache key."""
    if not spec:
        return ""
    canon = {k: v for k, v in sorted(spec.items()) if k != "hash"}
    return hashlib.sha256(
        json.dumps(canon, sort_keys=True).encode()).hexdigest()[:16]


def materialize(spec: Dict[str, Any], kv_get, root: str
                ) -> Tuple[Optional[str], List[str]]:
    """Worker side: download + extract packages under ``root``.

    Returns (cwd or None, sys.path additions).  Extraction is
    idempotent + concurrency-safe: extract to a pid-suffixed temp dir,
    then atomically rename into the content-addressed location.
    """

    def extract(digest: str) -> str:
        dest = os.path.join(root, digest)
        if os.path.isdir(dest):
            return dest
        data = kv_get(_PKG_PREFIX + digest)
        if data is None:
            raise RuntimeError(
                f"runtime_env package {digest} missing from cluster KV")
        tmp = f"{dest}.tmp.{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)  # raced; loser cleans up
        return dest

    cwd = None
    paths: List[str] = []
    if spec.get("working_dir_pkg"):
        cwd = extract(spec["working_dir_pkg"])
        paths.append(cwd)
    for entry in spec.get("py_modules_pkgs", []):
        # Each module dir X becomes importable as "X": extract the
        # package and put its PARENT on sys.path via a named alias dir.
        base = extract(entry["pkg"])
        alias_root = os.path.join(root, f"mod-{entry['pkg']}")
        alias = os.path.join(alias_root, entry["name"])
        if not os.path.isdir(alias):
            os.makedirs(alias_root, exist_ok=True)
            try:
                os.symlink(base, alias)
            except OSError:
                pass
        paths.append(alias_root)
    return cwd, paths
