"""Trampoline: build/reuse the env's uv venv, then exec worker_main
inside it (see uv.py; ref: _private/runtime_env/uv.py)."""

import sys

from .uv import bootstrap_main

if __name__ == "__main__":
    sys.exit(bootstrap_main())
