"""uv runtime-env plugin — hash-keyed cached venvs built with uv.

Role-equivalent to the reference's uv plugin (ref:
python/ray/_private/runtime_env/uv.py — same shape as pip.py but the
resolver/installer is the uv binary, ~10-100x faster for cached
wheels).  Identical contract to our pip plugin (pip.py): the worker
STARTS inside the env via a bootstrap trampoline, venvs are keyed by
(requirements, python version) and shared across workers under a file
lock, and the cluster stack (jax/libtpu/flax) is inherited through
system-site-packages.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import sys
from typing import List

from .pip import _OK_MARKER, _venv_python, normalize_pip

normalize_uv = normalize_pip  # same two spellings, same ordering rule


def uv_available() -> bool:
    return shutil.which("uv") is not None


def venv_key(packages: List[str]) -> str:
    payload = json.dumps(
        {"reqs": list(packages), "py": sys.version_info[:2],
         "tool": "uv"}, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def ensure_uv_venv(packages: List[str], cache_root: str,
                   log=None) -> str:
    """Build (or reuse) a uv-managed venv; returns its python path.
    Concurrent-safe via flock, like pip.ensure_venv."""
    import fcntl

    packages = normalize_uv(packages)
    if not uv_available():
        raise RuntimeError(
            "runtime_env['uv'] requested but no `uv` binary is on "
            "PATH on this node")
    key = venv_key(packages)
    os.makedirs(cache_root, exist_ok=True)
    venv_dir = os.path.join(cache_root, f"uv-{key}")
    marker = os.path.join(venv_dir, _OK_MARKER)
    if os.path.exists(marker):
        return _venv_python(venv_dir)
    lock_path = os.path.join(cache_root, f"uv-{key}.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(marker):
            return _venv_python(venv_dir)
        if log:
            log(f"building uv venv {key} for {packages}")
        tmp = f"{venv_dir}.tmp.{os.getpid()}"
        proc = subprocess.run(
            ["uv", "venv", "--system-site-packages",
             "--python", sys.executable, tmp],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"uv venv failed:\n{proc.stderr[-2000:]}")
        # Same parent-site .pth bridge as pip.py: when the cluster
        # python is itself a venv, its site-packages must stay
        # importable beneath the new env's own installs.
        import glob as _glob

        venv_site = _glob.glob(os.path.join(
            tmp, "lib", "python*", "site-packages"))[0]
        parent_sites = [p for p in sys.path
                        if p.endswith("site-packages")
                        and os.path.isdir(p)]
        if parent_sites:
            with open(os.path.join(venv_site,
                                   "_rt_parent_site.pth"), "w") as f:
                f.write("\n".join(parent_sites) + "\n")
        if any(not x.startswith("-") for x in packages):
            proc = subprocess.run(
                ["uv", "pip", "install",
                 "--python", _venv_python(tmp), *packages],
                capture_output=True, text=True)
            if proc.returncode != 0:
                shutil.rmtree(tmp, ignore_errors=True)
                raise RuntimeError(
                    f"uv pip install failed for {packages}:\n"
                    f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
        if os.path.isdir(venv_dir):
            shutil.rmtree(venv_dir, ignore_errors=True)
        os.replace(tmp, venv_dir)
        with open(marker, "w") as f:
            f.write("\n".join(packages))
        return _venv_python(venv_dir)


def bootstrap_main() -> int:
    """Agent-spawned trampoline (``python -m
    ray_tpu.runtime_env.uv_bootstrap``): land the worker inside its
    uv venv; a failed build poisons the worker via
    RT_RUNTIME_ENV_ERROR instead of exiting (see pip.bootstrap_main
    for why)."""
    spec = json.loads(os.environ.get("RT_RUNTIME_ENV", "{}"))
    packages = spec.get("uv") or []
    from ray_tpu.core.config import RuntimeConfig

    cfg = RuntimeConfig.from_env()
    cache_root = os.path.join(
        cfg.session_dir_root,
        os.environ.get("RT_SESSION_NAME", "default"), "uv_envs")
    try:
        python = ensure_uv_venv(packages, cache_root,
                                log=lambda m: print(m, flush=True))
    except Exception as e:  # noqa: BLE001 — poisoned worker reports it
        print(f"uv env build failed: {e!r}", flush=True)
        os.environ["RT_RUNTIME_ENV_ERROR"] = \
            f"uv env build failed: {e}"[:2000]
        python = sys.executable
    os.execv(python, [python, "-u", "-m", "ray_tpu.core.worker_main"])
    return 0  # unreachable
