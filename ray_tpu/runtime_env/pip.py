"""pip/virtualenv runtime-env plugin.

Role-equivalent to the reference's pip plugin (ref:
python/ray/_private/runtime_env/pip.py — hash-keyed cached virtualenv
per requirement set, workers run inside it; uv.py is the same shape).
TPU adaptation: venvs are created with ``--system-site-packages`` so
the heavyweight cluster stack (jax/libtpu/flax) is inherited, and only
the env's extra requirements install into the venv.

The worker STARTS inside the env: the node agent spawns
``python -m ray_tpu.runtime_env.pip_bootstrap`` (cluster python),
which builds-or-reuses the venv under a file lock and then execs the
venv's python as ``ray_tpu.core.worker_main`` — the agent's event loop
never blocks on a pip install, and concurrent workers of the same env
share one build (ref: pip.py's per-URI lock + worker startup hook).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
from typing import List, Optional

_OK_MARKER = ".rt_venv_ok"


def normalize_pip(value) -> List[str]:
    """Accept ``["pkg==1.0", ...]`` or ``{"packages": [...]}`` (the
    reference's two spellings).  ORDER IS PRESERVED: entries may be
    pip flags whose value is the next entry (``["--index-url", URL,
    "pkg"]``) — sorting would orphan them."""
    if isinstance(value, dict):
        value = value.get("packages", [])
    if not isinstance(value, (list, tuple)) or not all(
            isinstance(x, str) for x in value):
        raise TypeError(
            "runtime_env['pip'] must be a list of requirement strings "
            "or {'packages': [...]}")
    return list(value)


def venv_key(packages: List[str]) -> str:
    """Cache key: requirements + interpreter version (a venv built for
    one python minor version is not valid for another)."""
    payload = json.dumps(
        {"reqs": list(packages),
         "py": sys.version_info[:2]}, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _venv_python(venv_dir: str) -> str:
    return os.path.join(venv_dir, "bin", "python")


def ensure_venv(packages: List[str], cache_root: str,
                log=None) -> str:
    """Build (or reuse) the venv for ``packages``; returns its python
    executable path.  Safe under concurrent callers via flock."""
    import fcntl

    packages = normalize_pip(packages)
    key = venv_key(packages)
    os.makedirs(cache_root, exist_ok=True)
    venv_dir = os.path.join(cache_root, f"venv-{key}")
    marker = os.path.join(venv_dir, _OK_MARKER)
    if os.path.exists(marker):
        return _venv_python(venv_dir)
    lock_path = os.path.join(cache_root, f"venv-{key}.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(marker):   # another worker built it
            return _venv_python(venv_dir)
        if log:
            log(f"building pip venv {key} for {packages}")
        tmp = f"{venv_dir}.tmp.{os.getpid()}"
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             tmp], check=True, capture_output=True)
        # --system-site-packages resolves to the BASE prefix; when the
        # cluster python is itself a venv (common), its site-packages
        # (jax/libtpu/setuptools) would be invisible — link them in via
        # a .pth.  The venv's own installs still shadow them (its
        # site-packages sorts first).
        import glob as _glob

        venv_site = _glob.glob(os.path.join(
            tmp, "lib", "python*", "site-packages"))[0]
        parent_sites = [p for p in sys.path
                        if p.endswith("site-packages")
                        and os.path.isdir(p)]
        if parent_sites:
            with open(os.path.join(venv_site,
                                   "_rt_parent_site.pth"), "w") as f:
                f.write("\n".join(parent_sites) + "\n")
        # The list passes to pip IN ORDER (flags keep their values);
        # install only when something beyond bare flags is present.
        if any(not x.startswith("-") for x in packages):
            proc = subprocess.run(
                [_venv_python(tmp), "-m", "pip", "install",
                 "--disable-pip-version-check", *packages],
                capture_output=True, text=True)
            if proc.returncode != 0:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
                raise RuntimeError(
                    f"pip install failed for {packages}:\n"
                    f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
        if os.path.isdir(venv_dir):  # stale partial build (no marker)
            import shutil

            shutil.rmtree(venv_dir, ignore_errors=True)
        os.replace(tmp, venv_dir)
        with open(marker, "w") as f:
            f.write("\n".join(packages))
        return _venv_python(venv_dir)


def bootstrap_main() -> int:
    """Entry for ``python -m ray_tpu.runtime_env.pip_bootstrap``: the
    agent-spawned trampoline that lands the worker inside its venv.
    A FAILED env build still execs a (base-python) worker, poisoned
    via RT_RUNTIME_ENV_ERROR: it registers normally and fails its
    tasks fast with RuntimeEnvSetupError — exiting here instead would
    send the agent into an infinite respawn/reinstall loop."""
    spec = json.loads(os.environ.get("RT_RUNTIME_ENV", "{}"))
    packages = spec.get("pip") or []
    from ray_tpu.core.config import RuntimeConfig

    cfg = RuntimeConfig.from_env()
    cache_root = os.path.join(
        cfg.session_dir_root,
        os.environ.get("RT_SESSION_NAME", "default"), "pip_envs")
    try:
        python = ensure_venv(packages, cache_root,
                             log=lambda m: print(m, flush=True))
    except Exception as e:  # noqa: BLE001 — poisoned worker reports it
        print(f"pip env build failed: {e!r}", flush=True)
        os.environ["RT_RUNTIME_ENV_ERROR"] = \
            f"pip env build failed: {e}"[:2000]
        python = sys.executable
    os.execv(python, [python, "-u", "-m", "ray_tpu.core.worker_main"])
    return 0  # unreachable
