"""Trampoline: build/reuse the env's venv, then exec worker_main inside
it (see pip.py; ref: _private/runtime_env/pip.py worker startup)."""

import sys

from .pip import bootstrap_main

if __name__ == "__main__":
    sys.exit(bootstrap_main())
