"""Chaos killers: background threads that keep killing cluster pieces.

Role-equivalent to the reference's chaos fixtures (ref:
python/ray/_private/test_utils.py — NodeKillerBase:1581 kills raylets,
WorkerKillerActor:1678 kills task workers mid-run).  Process-based
rather than actor-based: the single-machine Cluster fixture exposes the
OS processes directly, so killers operate on pids — the failure the
system sees (SIGKILL, no goodbye) is identical.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import List, Optional


class _KillerThread:
    def __init__(self, interval_s: float, seed: int,
                 max_kills: int = 0):
        self._interval = interval_s
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._max_kills = max_kills  # 0 = unbounded
        self.kills: List[int] = []

    def start(self) -> "_KillerThread":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            if self._max_kills and len(self.kills) >= self._max_kills:
                return
            try:
                pid = self._pick()
            except Exception:
                continue
            if pid is None:
                continue
            try:
                self._kill(pid)
                self.kills.append(pid)
            except (ProcessLookupError, PermissionError):
                pass

    def _kill(self, pid: int) -> None:
        os.kill(pid, signal.SIGKILL)

    def _pick(self) -> Optional[int]:  # pragma: no cover - abstract
        raise NotImplementedError


class NodeKiller(_KillerThread):
    """Kills a random non-head node agent from a Cluster fixture (ref:
    NodeKillerBase)."""

    def __init__(self, cluster, interval_s: float = 5.0, seed: int = 0,
                 spare_head: bool = True, max_kills: int = 0):
        super().__init__(interval_s, seed, max_kills)
        self._cluster = cluster
        self._spare_head = spare_head

    def _pick(self) -> Optional[int]:
        nodes = list(self._cluster.nodes)
        if self._spare_head and nodes:
            nodes = nodes[1:]
        live = [n for n in nodes if n.proc.poll() is None]
        if not live:
            return None
        victim = self._rng.choice(live)
        return victim.proc.pid


class PreemptionKiller(_KillerThread):
    """Mirrors a real GCP spot preemption: the victim node agent gets
    SIGTERM (the preemption notice — it enters DRAINING and the
    training plane races a checkpoint-on-notice), then after the
    configured grace the whole node dies hard — SIGKILL to the agent
    AND every worker process it hosts, like the VM vanishing (ref:
    NodeKillerBase, plus the GCP preemption-notice semantics the
    drain plane exists for)."""

    def __init__(self, cluster, interval_s: float = 10.0,
                 grace_s: float = 3.0, seed: int = 0,
                 spare_head: bool = True, max_kills: int = 0):
        super().__init__(interval_s, seed, max_kills)
        self._cluster = cluster
        self._grace = grace_s
        self._spare_head = spare_head

    def _pick(self):
        nodes = list(self._cluster.nodes)
        if self._spare_head and nodes:
            nodes = nodes[1:]
        live = [n for n in nodes if n.proc.poll() is None]
        if not live:
            return None
        return self._rng.choice(live)

    def _kill(self, node) -> None:
        preempt_node_processes(node, self._grace,
                               stop_event=self._stop)


def _agent_worker_pids(agent_addr: str) -> List[int]:
    """Worker pids of a (single-machine test) node agent, via its
    list_workers RPC — the processes a real VM death would take out
    along with the agent."""
    import asyncio

    from ..core.rpc import RpcClient

    async def _go():
        cli = RpcClient(agent_addr, connect_timeout=5.0)
        try:
            r = await cli.call("list_workers", {})
            return [w["pid"] for w in r.get("workers", [])]
        finally:
            await cli.close()

    try:
        return asyncio.run(_go())
    except Exception:
        return []


def preempt_node_processes(node, grace_s: float,
                           stop_event: Optional[threading.Event] = None
                           ) -> None:
    """SIGTERM the agent (preemption notice), wait ``grace_s``, then
    SIGKILL the agent and every worker it hosted — the full lifecycle
    of an announced VM death.  ``node`` is a cluster_utils.NodeHandle
    (or anything with .proc and .agent_addr)."""
    worker_pids = _agent_worker_pids(node.agent_addr)
    try:
        node.proc.terminate()  # the notice
    except (ProcessLookupError, PermissionError):
        pass
    if stop_event is not None:
        stop_event.wait(grace_s)
    else:
        time.sleep(grace_s)
    for pid in [node.proc.pid] + worker_pids:
        try:
            os.kill(pid, signal.SIGKILL)  # the VM dies
        except (ProcessLookupError, PermissionError):
            pass
    try:
        node.proc.wait(timeout=5)
    except Exception:
        pass


def _controller_call(address: str, method: str, payload=None):
    import asyncio

    from ..core.rpc import RpcClient

    async def _go():
        cli = RpcClient(address, connect_timeout=5.0)
        try:
            return await cli.call(method, payload or {})
        finally:
            await cli.close()

    return asyncio.run(_go())


class ReplicaKiller(_KillerThread):
    """SIGKILLs a random SERVE REPLICA worker by pid — the chaos the
    request-resilience plane exists for (failover retries + circuit
    breakers must absorb the death before the serve controller's
    health probe replaces the actor).  Replica workers are found by
    cross-referencing the controller's actor table (class ``_Replica``)
    with each node agent's worker inventory, exactly the processes a
    crashing model server would take out (ref: WorkerKillerActor, but
    aimed at serve replicas specifically)."""

    def __init__(self, cluster, interval_s: float = 2.0, seed: int = 0,
                 max_kills: int = 0):
        super().__init__(interval_s, seed, max_kills)
        self._cluster = cluster

    def _replica_actor_ids(self) -> set:
        actors = _controller_call(self._cluster.address,
                                  "list_actors") or []
        out = set()
        for a in actors:
            if a.get("class_name") == "_Replica":
                aid = a.get("actor_id")
                out.add(aid.hex() if hasattr(aid, "hex") else str(aid))
        return out

    def _pick(self) -> Optional[int]:
        replicas = self._replica_actor_ids()
        if not replicas:
            return None
        pids: List[int] = []
        for node in self._cluster.nodes:
            if node.proc.poll() is not None:
                continue
            try:
                import asyncio

                from ..core.rpc import RpcClient

                async def _go(addr=node.agent_addr):
                    cli = RpcClient(addr, connect_timeout=5.0)
                    try:
                        return await cli.call("list_workers", {})
                    finally:
                        await cli.close()

                info = asyncio.run(_go())
            except Exception:
                continue
            for w in info.get("workers", []):
                if w.get("actor_id") in replicas:
                    pids.append(w["pid"])
        if not pids:
            return None
        return self._rng.choice(pids)


class TornWriteInjector:
    """SIGKILLs a saving process mid-shard-write — the torn-write
    chaos the crash-atomic checkpoint commit exists for.  A watcher
    thread polls the run directory for an in-progress staging dir
    (``checkpoint_*.tmp/``) containing at least ``min_files`` data
    files, then kills the target pid dead, leaving exactly the
    half-written state a preemption SIGKILL at the grace deadline
    leaves.  ``find_latest_in``/restore must then land on the last
    COMMITTED checkpoint and ``rt doctor`` must name the torn dir."""

    def __init__(self, run_dir: str, pid: int,
                 min_files: int = 1, poll_s: float = 0.002):
        self.run_dir = run_dir
        self.pid = pid
        self.min_files = min_files
        self._poll = poll_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.killed_at: Optional[str] = None  # the tmp dir we tore

    def start(self) -> "TornWriteInjector":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def _staging_files(self):
        import glob

        for tmp in glob.glob(os.path.join(self.run_dir,
                                          "checkpoint_*.tmp")):
            files = glob.glob(os.path.join(tmp, "shard_*", "*.npy")) \
                + glob.glob(os.path.join(tmp, "*.msgpack")) \
                + glob.glob(os.path.join(tmp, "shard_*", "*.npy.tmp"))
            if len(files) >= self.min_files:
                return tmp
        return None

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                tmp = self._staging_files()
            except OSError:
                continue
            if tmp is None:
                continue
            try:
                os.kill(self.pid, signal.SIGKILL)
                self.killed_at = tmp
            except (ProcessLookupError, PermissionError):
                pass
            return


class WorkerKiller(_KillerThread):
    """Kills a random live worker process of the given agents (ref:
    WorkerKillerActor — kills the process executing a task, exercising
    retry paths)."""

    def __init__(self, agent_call, interval_s: float = 2.0,
                 seed: int = 0, max_kills: int = 0):
        """``agent_call(method, payload)`` reaches a node agent (e.g.
        ``runtime.agent_call``)."""
        super().__init__(interval_s, seed, max_kills)
        self._agent_call = agent_call

    def _pick(self) -> Optional[int]:
        info = self._agent_call("list_workers", {})
        pids = [w["pid"] for w in info.get("workers", [])
                if w.get("state") in ("leased", "actor")]
        if not pids:
            return None
        return self._rng.choice(pids)
