"""ray_tpu.testing — fault-injection helpers for tests.

Role-equivalent to the reference's chaos test utilities (ref:
python/ray/_private/test_utils.py:1511 ResourceKillerActor /
NodeKillerBase / WorkerKillerActor).
"""

from .chaos import (NodeKiller, PreemptionKiller,  # noqa
                    ReplicaKiller, TornWriteInjector, WorkerKiller,
                    preempt_node_processes)
