"""Tuner + TuneController — concurrent trial execution.

Role-equivalent to the reference's Tuner.fit -> TuneController (ref:
python/ray/tune/tuner.py:44, tune/execution/tune_controller.py): expand
the param space into trials, run up to ``max_concurrent`` trial actors,
stream their reports, let the scheduler stop under-performers, and
return a ResultGrid.  Trainables are functions ``fn(config)`` that call
``ray_tpu.tune.report(...)`` — or a BaseTrainer, whose param space merges
into its train_loop_config (the reference's trainer-as-trainable wrap,
base_trainer.py:724).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ..train.config import Result, RunConfig
from .schedulers import COMPLETE, CONTINUE, FIFOScheduler, STOP
from .search import BasicVariantGenerator

_trial_session = None  # set inside trial processes


@dataclass
class TuneConfig:
    num_samples: int = 1
    metric: Optional[str] = None
    mode: str = "min"
    scheduler: Any = None
    max_concurrent_trials: int = 2
    seed: Optional[int] = None
    # A search.Searcher (e.g. TPESearcher): configs are suggested one
    # trial at a time, informed by completed results, instead of the
    # up-front BasicVariantGenerator expansion (ref: tune/search/).
    search_alg: Any = None


@ray_tpu.remote(max_concurrency=4)
class _TrialActor:
    """Runs one trial's function; buffers its reports."""

    def __init__(self):
        self.reports: List[Dict] = []
        self.iteration = 0
        self.checkpoint: Any = None

    def run(self, fn_payload: bytes, config: Dict,
            checkpoint: Any = None, start_iteration: int = 0):
        import cloudpickle

        from ray_tpu.tune import tuner as tuner_mod

        fn = cloudpickle.loads(fn_payload)
        self.checkpoint = checkpoint
        self.iteration = start_iteration
        tuner_mod._trial_session = self
        try:
            return fn(config)
        finally:
            tuner_mod._trial_session = None

    def _record(self, metrics: Dict, checkpoint: Any = None):
        self.iteration += 1
        row = dict(metrics)
        row.setdefault("training_iteration", self.iteration)
        if checkpoint is not None:
            self.checkpoint = checkpoint
            row["__checkpoint__"] = checkpoint
        self.reports.append(row)

    def poll(self):
        out, self.reports = self.reports, []
        return out


def report(metrics: Dict[str, Any], checkpoint=None) -> None:
    """Called inside a trial fn (ref: tune.report / session.report).
    ``checkpoint`` (any picklable value) becomes the trial's restore
    point — PBT exploits clone it into other trials."""
    if _trial_session is None:
        raise RuntimeError("tune.report() called outside a trial")
    _trial_session._record(metrics, checkpoint)


def get_checkpoint() -> Any:
    """Inside a trial fn: the checkpoint to resume from (None on a
    fresh start; ref: tune.get_checkpoint)."""
    if _trial_session is None:
        raise RuntimeError("tune.get_checkpoint() outside a trial")
    return getattr(_trial_session, "checkpoint", None)


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    actor: Any = None
    run_ref: Any = None
    status: str = "PENDING"   # PENDING|RUNNING|TERMINATED|STOPPED|ERROR
    history: List[Dict] = field(default_factory=list)
    error: Optional[BaseException] = None
    checkpoint: Any = None     # latest tune.report(checkpoint=...) value
    num_restarts: int = 0      # PBT exploit restarts
    # Exploit provenance: (source_trial_id, source_score) per exploit,
    # so tests/analysis can verify adoption continuity (ref: pbt.py
    # logging the exploit decision into trial metadata).
    exploits: List[Any] = field(default_factory=list)

    def last_metrics(self) -> Dict:
        return self.history[-1] if self.history else {}


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self.trials)

    def __iter__(self):
        for t in self.trials:
            yield Result(metrics=t.last_metrics(), error=t.error,
                         metrics_history=t.history)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        best: Optional[Trial] = None
        for t in self.trials:
            if t.error is not None or metric not in t.last_metrics():
                continue
            if best is None:
                best = t
                continue
            a, b = t.last_metrics()[metric], best.last_metrics()[metric]
            if (mode == "min" and a < b) or (mode == "max" and a > b):
                best = t
        if best is None:
            raise ValueError(f"no trial reported metric {metric!r}")
        return Result(metrics=best.last_metrics(), error=None,
                      metrics_history=best.history)

    @property
    def best_config(self) -> Dict:
        best = self.get_best_result()
        for t in self.trials:
            if t.last_metrics() == best.metrics:
                return t.config
        return {}


class Tuner:
    def __init__(self, trainable: Any, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def _as_function(self) -> Callable[[Dict], Any]:
        from ..train.trainer import BaseTrainer

        if isinstance(self.trainable, BaseTrainer):
            trainer = self.trainable

            def run_trainer(config: Dict):
                import copy

                from ray_tpu.tune import tuner as tuner_mod

                t = copy.copy(trainer)
                t.train_loop_config = {**trainer.train_loop_config,
                                       **config}
                result = t.fit()
                if result.error is not None:
                    raise result.error
                for h in result.metrics_history:
                    tuner_mod.report(h["metrics"])
                return result.metrics

            return run_trainer
        return self.trainable

    def fit(self) -> ResultGrid:
        from ..core import serialization

        tc = self.tune_config
        fn = self._as_function()
        serialization.ensure_code_portable(fn)
        serialization.ensure_code_portable(self.trainable)
        import cloudpickle

        payload = cloudpickle.dumps(fn)
        searcher = tc.search_alg
        trials: List[Trial]
        if searcher is not None:
            searcher.setup(self.param_space, tc.metric, tc.mode,
                           tc.seed)
            trials = []
            pending: List[Trial] = []
            to_suggest = tc.num_samples
        else:
            variants = BasicVariantGenerator(
                self.param_space, tc.num_samples, tc.seed).variants()
            trials = [
                Trial(trial_id=f"trial_{i:04d}_{uuid.uuid4().hex[:6]}",
                      config=cfg) for i, cfg in enumerate(variants)]
            pending = list(trials)
            to_suggest = 0

        def _next_trial() -> Optional[Trial]:
            nonlocal to_suggest
            if pending:
                return pending.pop(0)
            if to_suggest > 0:
                to_suggest -= 1
                tid = (f"trial_{len(trials):04d}_"
                       f"{uuid.uuid4().hex[:6]}")
                t = Trial(trial_id=tid, config=searcher.suggest(tid))
                trials.append(t)
                return t
            return None

        def _completed(t: Trial) -> None:
            if searcher is not None:
                try:
                    searcher.on_trial_complete(t.trial_id,
                                               t.last_metrics())
                except Exception:
                    pass

        scheduler = tc.scheduler or FIFOScheduler()
        running: List[Trial] = []
        while pending or running or to_suggest:
            while len(running) < tc.max_concurrent_trials:
                t = _next_trial()
                if t is None:
                    break
                t.actor = _TrialActor.remote()
                t.run_ref = t.actor.run.remote(payload, t.config)
                t.status = "RUNNING"
                running.append(t)
            if not running:
                continue
            # Poll reports and completion.
            done_refs, _ = ray_tpu.wait([t.run_ref for t in running],
                                        num_returns=1, timeout=0.2)
            pop_hook = getattr(scheduler, "on_population_result", None)
            for t in list(running):
                exploit_decision = None
                stopped = False
                # Consume the WHOLE batch (poll() already popped it from
                # the actor) before acting on any decision — dropping
                # the tail would lose metrics and checkpoints forever.
                for row in ray_tpu.get(t.actor.poll.remote()):
                    if "__checkpoint__" in row:
                        t.checkpoint = row.pop("__checkpoint__")
                    t.history.append(row)
                    if stopped or exploit_decision is not None:
                        continue
                    decision = scheduler.on_result(t.trial_id, row)
                    if decision in (STOP, COMPLETE) and \
                            t.status == "RUNNING":
                        t.status = ("STOPPED" if decision == STOP
                                    else "TERMINATED")
                        stopped = True
                        continue
                    if pop_hook is not None and t.status == "RUNNING":
                        pdec = pop_hook(t, row, trials)
                        if isinstance(pdec, dict) and "exploit" in pdec:
                            exploit_decision = pdec
                if stopped:
                    ray_tpu.kill(t.actor)
                    running.remove(t)
                    _completed(t)
                    continue
                if exploit_decision is not None:
                    # PBT: adopt the source's checkpoint + mutated
                    # config and restart the trial, continuing the
                    # iteration clock so perturbation windows and rung
                    # milestones stay monotonic.
                    source = exploit_decision["exploit"]
                    ray_tpu.kill(t.actor)
                    t.config = exploit_decision["config"]
                    t.checkpoint = source.checkpoint
                    t.num_restarts += 1
                    t.exploits.append(
                        (source.trial_id,
                         source.last_metrics().get(tc.metric)))
                    last_iter = max(
                        (r.get("training_iteration", 0)
                         for r in t.history), default=0)
                    t.actor = _TrialActor.remote()
                    t.run_ref = t.actor.run.remote(
                        payload, t.config, t.checkpoint, last_iter)
                    continue
                if t.status != "RUNNING":
                    continue
                if t.run_ref in done_refs:
                    try:
                        ray_tpu.get(t.run_ref)
                        # Final poll for reports emitted just before exit.
                        try:
                            for row in ray_tpu.get(t.actor.poll.remote()):
                                if "__checkpoint__" in row:
                                    t.checkpoint = \
                                        row.pop("__checkpoint__")
                                t.history.append(row)
                        except Exception:
                            pass
                        t.status = "TERMINATED"
                    except Exception as e:  # noqa: BLE001
                        t.error = e
                        t.status = "ERROR"
                    ray_tpu.kill(t.actor)
                    running.remove(t)
                    _completed(t)
        return ResultGrid(trials, tc.metric, tc.mode)
