"""Trial schedulers: FIFO and ASHA (asynchronous successive halving).

Role-equivalent to the reference's tune.schedulers (ref:
python/ray/tune/schedulers/async_hyperband.py ASHAScheduler).  The
controller calls ``on_result`` for every report; the scheduler answers
CONTINUE or STOP.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"          # culled by the scheduler (under-performing)
COMPLETE = "COMPLETE"  # budget (max_t) reached — a normal finish


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous successive halving on ``metric`` at rungs
    grace_period * reduction_factor^k."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> list of recorded metric values
        self.recorded: Dict[int, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return COMPLETE  # budget exhausted — not a cull
        for rung in reversed(self.rungs):
            if t == rung:
                peers = self.recorded[rung]
                peers.append(float(value))
                if len(peers) < self.eta:
                    return CONTINUE  # not enough peers; be optimistic
                ranked = sorted(peers)
                if self.mode == "max":
                    ranked = ranked[::-1]
                cutoff_idx = max(len(ranked) // self.eta - 1, 0)
                cutoff = ranked[cutoff_idx]
                good = (value <= cutoff if self.mode == "min"
                        else value >= cutoff)
                return CONTINUE if good else STOP
        return CONTINUE


class PopulationBasedTraining:
    """PBT (ref: tune/schedulers/pbt.py; the public PBT paper): every
    ``perturbation_interval`` iterations a bottom-quantile trial
    EXPLOITs a top-quantile trial (adopting its checkpoint) and
    EXPLOREs by mutating hyperparameters.

    Population-level decisions need population state, so this scheduler
    implements ``on_population_result(trial, result, trials)`` and
    returns either CONTINUE or a dict
    {"exploit": source_trial, "config": mutated_config} which the Tuner
    applies by restarting the trial from the source's checkpoint.
    """

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 time_attr: str = "training_iteration",
                 seed: int = 0):
        import random

        assert 0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self.num_exploits = 0

    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE  # population hook drives PBT

    # ------------------------------------------------------------- explore
    def _mutate(self, config: Dict) -> Dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, (list, tuple)):
                # Perturb to an ADJACENT list entry (ref: pbt.py
                # _explore — list-valued hyperparams step to a
                # neighboring index, they are not re-drawn uniformly;
                # a uniform draw can hand the exploited trial the very
                # value it is being rescued from).
                choices = list(spec)
                try:
                    idx = choices.index(out[key])
                except ValueError:
                    out[key] = self._rng.choice(choices)
                    continue
                if len(choices) == 1:
                    continue
                if idx == 0:
                    idx = 1
                elif idx == len(choices) - 1:
                    idx = len(choices) - 2
                else:
                    idx = idx + self._rng.choice((-1, 1))
                out[key] = choices[idx]
            else:  # continuous: the classic 0.8x / 1.2x perturbation
                factor = self._rng.choice((0.8, 1.2))
                out[key] = type(out[key])(out[key] * factor)
        return out

    # ------------------------------------------------------------- exploit
    def on_population_result(self, trial, result: Dict, trials):
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        # Rank the population by its latest metric.
        scored = []
        for other in trials:
            m = other.last_metrics().get(self.metric)
            if m is not None:
                scored.append((float(m), other))
        if len(scored) < 2:
            return CONTINUE
        reverse = self.mode == "max"
        scored.sort(key=lambda x: x[0], reverse=reverse)
        k = max(1, int(len(scored) * self.quantile))
        top = [tr for _, tr in scored[:k]]
        bottom = {tr.trial_id for _, tr in scored[-k:]}
        if trial.trial_id not in bottom or trial in top:
            return CONTINUE
        source = self._rng.choice(
            [tr for tr in top if tr.trial_id != trial.trial_id]
            or [top[0]])
        if source.checkpoint is None:
            return CONTINUE  # nothing to clone yet
        self.num_exploits += 1
        return {"exploit": source,
                "config": self._mutate(source.config)}
