"""Trial schedulers: FIFO and ASHA (asynchronous successive halving).

Role-equivalent to the reference's tune.schedulers (ref:
python/ray/tune/schedulers/async_hyperband.py ASHAScheduler).  The
controller calls ``on_result`` for every report; the scheduler answers
CONTINUE or STOP.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"          # culled by the scheduler (under-performing)
COMPLETE = "COMPLETE"  # budget (max_t) reached — a normal finish


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous successive halving on ``metric`` at rungs
    grace_period * reduction_factor^k."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> list of recorded metric values
        self.recorded: Dict[int, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return COMPLETE  # budget exhausted — not a cull
        for rung in reversed(self.rungs):
            if t == rung:
                peers = self.recorded[rung]
                peers.append(float(value))
                if len(peers) < self.eta:
                    return CONTINUE  # not enough peers; be optimistic
                ranked = sorted(peers)
                if self.mode == "max":
                    ranked = ranked[::-1]
                cutoff_idx = max(len(ranked) // self.eta - 1, 0)
                cutoff = ranked[cutoff_idx]
                good = (value <= cutoff if self.mode == "min"
                        else value >= cutoff)
                return CONTINUE if good else STOP
        return CONTINUE
