"""Search spaces and suggestion generation.

Role-equivalent to the reference's tune.search (ref:
python/ray/tune/search/ — BasicVariantGenerator, sample.py domains).
Domains: uniform/loguniform/randint/choice/grid_search; the basic
generator crosses grid axes and samples the rest per trial.
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low),
                                    math.log(self.high)))


@dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    options: List[Any]

    def sample(self, rng):
        return rng.choice(self.options)


@dataclass
class GridSearch:
    values: List[Any]


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(options: List[Any]) -> Choice:
    return Choice(list(options))


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


def sample_from(fn: Callable[[Dict], Any]):
    return _SampleFrom(fn)


@dataclass
class _SampleFrom:
    fn: Callable


class Searcher:
    """Sequential search algorithm interface (ref:
    tune/search/searcher.py Searcher — suggest/on_trial_complete).
    Pass an instance as ``TuneConfig(search_alg=...)``; the Tuner then
    asks for one config per trial as capacity frees up instead of
    expanding the space up front."""

    def setup(self, param_space: Dict[str, Any],
              metric: Optional[str], mode: str,
              seed: Optional[int]) -> None:
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any]) -> None:
        pass


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (Bergstra et al. 2011, the
    public algorithm behind Optuna's default sampler) — the model-based
    searcher the reference reaches through its Optuna adapter (ref:
    tune/search/optuna/optuna_search.py), implemented natively because
    the TPU image carries no optuna/hyperopt.

    After ``n_initial`` random trials, each numeric dimension models
    the observations as two kernel densities — the best ``gamma``
    quantile ("good") vs the rest — and suggestions maximize the
    good/bad likelihood ratio over ``n_candidates`` draws from the
    good density.  Categorical dimensions use smoothed category
    frequencies.  GridSearch axes are unsupported (grids enumerate;
    use the default generator)."""

    def __init__(self, n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24):
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._observed: List[Dict[str, Any]] = []   # config + score

    def setup(self, param_space, metric, mode, seed) -> None:
        super().setup(param_space, metric, mode, seed)
        if any(isinstance(v, GridSearch)
               for v in param_space.values()):
            raise ValueError(
                "TPESearcher does not support grid_search axes")
        self._pending: Dict[str, Dict[str, Any]] = {}

    # ----------------------------------------------------- unit mapping
    def _to_unit(self, dom: Domain, value: float) -> float:
        import math

        if isinstance(dom, LogUniform):
            lo, hi = math.log(dom.low), math.log(dom.high)
            return (math.log(value) - lo) / (hi - lo)
        lo, hi = float(dom.low), float(dom.high)
        return (value - lo) / (hi - lo) if hi > lo else 0.5

    def _from_unit(self, dom: Domain, u: float):
        import math

        u = min(max(u, 0.0), 1.0)
        if isinstance(dom, LogUniform):
            lo, hi = math.log(dom.low), math.log(dom.high)
            return math.exp(lo + u * (hi - lo))
        lo, hi = float(dom.low), float(dom.high)
        v = lo + u * (hi - lo)
        if isinstance(dom, RandInt):
            return min(int(dom.high) - 1, max(int(dom.low), round(v)))
        return v

    # --------------------------------------------------------- suggest
    def _split(self) -> tuple:
        obs = sorted(self._observed, key=lambda o: o["score"])
        n_good = max(1, int(len(obs) * self.gamma))
        return obs[:n_good], obs[n_good:]

    @staticmethod
    def _kde(points: List[float], x: float, bw: float) -> float:
        import math

        if not points:
            return 1.0
        return sum(math.exp(-0.5 * ((x - p) / bw) ** 2)
                   for p in points) / (len(points) * bw)

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        model_ready = len(self._observed) >= self.n_initial
        good, bad = self._split() if model_ready else ([], [])
        for key, dom in self.param_space.items():
            if isinstance(dom, Choice):
                if model_ready:
                    counts = {repr(o): 1.0 for o in dom.options}
                    for g in good:
                        counts[repr(g["config"][key])] = counts.get(
                            repr(g["config"][key]), 1.0) + 1.0
                    total = sum(counts.values())
                    r = self.rng.random() * total
                    acc = 0.0
                    for opt in dom.options:
                        acc += counts[repr(opt)]
                        if r <= acc:
                            cfg[key] = opt
                            break
                    else:
                        cfg[key] = dom.options[-1]
                else:
                    cfg[key] = dom.sample(self.rng)
            elif isinstance(dom, Domain):
                if model_ready:
                    gpts = [self._to_unit(dom, g["config"][key])
                            for g in good]
                    bpts = [self._to_unit(dom, b["config"][key])
                            for b in bad]
                    bw = max(0.05, 1.0 / max(len(gpts), 1) ** 0.5)
                    best_u, best_ratio = None, -1.0
                    for _ in range(self.n_candidates):
                        base = self.rng.choice(gpts) if gpts \
                            else self.rng.random()
                        u = base + self.rng.gauss(0.0, bw)
                        u = min(max(u, 0.0), 1.0)
                        ratio = (self._kde(gpts, u, bw)
                                 / (self._kde(bpts, u, bw) + 1e-12))
                        if ratio > best_ratio:
                            best_u, best_ratio = u, ratio
                    cfg[key] = self._from_unit(dom, best_u)
                else:
                    cfg[key] = dom.sample(self.rng)
            elif isinstance(dom, _SampleFrom):
                cfg[key] = dom.fn(cfg)
            else:
                cfg[key] = dom
        self._pending[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any]) -> None:
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or not result or self.metric not in result:
            return
        value = float(result[self.metric])
        score = value if self.mode == "min" else -value
        self._observed.append({"config": cfg, "score": score})


class BasicVariantGenerator:
    """Cross product of grid axes x num_samples random draws of the rest
    (ref: tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grid_values = [self.param_space[k].values for k in grid_keys]
        combos = list(itertools.product(*grid_values)) if grid_keys \
            else [()]
        out: List[Dict[str, Any]] = []
        for _ in range(self.num_samples):
            for combo in combos:
                cfg: Dict[str, Any] = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    elif isinstance(v, _SampleFrom):
                        cfg[k] = v.fn(cfg)
                    else:
                        cfg[k] = v
                out.append(cfg)
        return out
