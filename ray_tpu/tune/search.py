"""Search spaces and suggestion generation.

Role-equivalent to the reference's tune.search (ref:
python/ray/tune/search/ — BasicVariantGenerator, sample.py domains).
Domains: uniform/loguniform/randint/choice/grid_search; the basic
generator crosses grid axes and samples the rest per trial.
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low),
                                    math.log(self.high)))


@dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    options: List[Any]

    def sample(self, rng):
        return rng.choice(self.options)


@dataclass
class GridSearch:
    values: List[Any]


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(options: List[Any]) -> Choice:
    return Choice(list(options))


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


def sample_from(fn: Callable[[Dict], Any]):
    return _SampleFrom(fn)


@dataclass
class _SampleFrom:
    fn: Callable


class BasicVariantGenerator:
    """Cross product of grid axes x num_samples random draws of the rest
    (ref: tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grid_values = [self.param_space[k].values for k in grid_keys]
        combos = list(itertools.product(*grid_values)) if grid_keys \
            else [()]
        out: List[Dict[str, Any]] = []
        for _ in range(self.num_samples):
            for combo in combos:
                cfg: Dict[str, Any] = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    elif isinstance(v, _SampleFrom):
                        cfg[k] = v.fn(cfg)
                    else:
                        cfg[k] = v
                out.append(cfg)
        return out
