"""ray_tpu.tune — hyperparameter search over the cluster runtime.

Role-equivalent to the reference's Ray Tune (ref: SURVEY.md §2.4).
"""

from .schedulers import (ASHAScheduler, FIFOScheduler,  # noqa
                         PopulationBasedTraining)
from .search import (Searcher, TPESearcher, choice,  # noqa
                     grid_search, loguniform, randint, sample_from,
                     uniform)
from .tuner import (ResultGrid, TuneConfig, Tuner,  # noqa: F401
                    get_checkpoint, report)
