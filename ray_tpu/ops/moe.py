"""Mixture-of-Experts feed-forward with expert parallelism.

Fills SURVEY §2.3's EP row (absent from the reference, which delegates
MoE to user frameworks).  TPU-first formulation (GShard/Switch style,
public papers): routing is expressed as DENSE one-hot dispatch/combine
einsums over an [experts, capacity] buffer — no ragged all-to-all
primitive exists in XLA, and the dense-einsum form is exactly what GSPMD
partitions well: with expert weights sharded over the ``expert`` mesh
axis and tokens over ``data``, XLA lowers the dispatch/combine einsums
to all-to-alls over ICI automatically.

Components:
- top-k router with fp32 gating, probability renormalization over the
  chosen experts, and the Switch load-balancing auxiliary loss
  (fraction-of-tokens x mean-gate per expert, scaled by E);
- capacity enforcement (capacity_factor x tokens/experts): tokens over
  an expert's capacity are dropped (their combine weight is zero, so
  the residual stream passes them through unchanged);
- batched expert FFNs as single [E, ...] einsums (one MXU-friendly
  matmul per projection, not a Python loop over experts).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Drop-in replacement for a transformer MLP block."""

    d_model: int
    d_ff: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, t, d = x.shape
        e = self.num_experts
        s = b * t
        capacity = max(int(self.capacity_factor * s / e), self.top_k)
        xf = x.reshape(s, d)

        # ---- router (fp32: gating decisions must not flip in bf16)
        router = self.param("router",
                            nn.initializers.normal(0.02 / d ** 0.5),
                            (d, e), jnp.float32)
        logits = jnp.asarray(xf, jnp.float32) @ router          # [S, E]
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, gate_idx = jax.lax.top_k(probs, self.top_k)  # [S, K]
        # Renormalize over the selected experts.
        gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)

        # ---- Switch aux loss: E * sum_e f_e * P_e  (ref: the public
        # Switch Transformer formulation) — sown for the trainer to add.
        assign1 = jax.nn.one_hot(gate_idx[:, 0], e)             # top-1
        f = assign1.mean(0)
        p = probs.mean(0)
        self.sow("intermediates", "moe_aux", e * jnp.sum(f * p))

        # ---- capacity: position of each (token, k) within its expert.
        onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)   # [S,K,E]
        flatk = onehot.reshape(s * self.top_k, e)  # k-major per token
        pos = jnp.cumsum(flatk, axis=0) - flatk                 # [SK, E]
        pos = (pos * flatk).sum(-1).reshape(s, self.top_k)      # [S, K]
        keep = pos < capacity
        gate_vals = gate_vals * keep

        # ---- dispatch/combine one-hots: [S, K, E, C]
        pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity),
                                capacity, dtype=self.dtype)
        disp = (jnp.asarray(onehot, self.dtype)[..., None]
                * pos_oh[:, :, None, :])                        # [S,K,E,C]
        dispatch = disp.sum(1)                                  # [S, E, C]
        combine = (disp * jnp.asarray(gate_vals, self.dtype)
                   [:, :, None, None]).sum(1)                   # [S, E, C]

        # ---- expert FFNs, batched over E.
        w_in = self.param("w_in", nn.initializers.normal(0.02),
                          (e, d, self.d_ff), jnp.float32)
        w_out = self.param("w_out", nn.initializers.normal(0.02),
                           (e, self.d_ff, d), jnp.float32)
        expert_in = jnp.einsum("sec,sd->ecd", dispatch,
                               jnp.asarray(xf, self.dtype))     # [E,C,D]
        h = jnp.einsum("ecd,edf->ecf", expert_in,
                       jnp.asarray(w_in, self.dtype))
        h = nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h,
                         jnp.asarray(w_out, self.dtype))        # [E,C,D]
        y = jnp.einsum("sec,ecd->sd", combine, out)             # [S, D]
        return y.reshape(b, t, d)


def moe_param_axes(path: str, leaf) -> Optional[Tuple]:
    """Logical axes for MoE params (None = not a MoE param)."""
    if "router" in path:
        return ("embed_fsdp", None)
    if "w_in" in path:
        return ("expert", "embed_fsdp", "mlp")
    if "w_out" in path:
        return ("expert", "mlp", "embed_fsdp")
    return None
