"""Blockwise (flash) causal attention as a Pallas TPU kernel.

Why: dense attention materializes the [B, H, T, T] score matrix in HBM —
at GPT-2 pretraining shapes that is ~400 MB of fp32 traffic per pass and
the single largest bandwidth consumer in the step.  The blockwise kernel
keeps scores in VMEM with the online-softmax recurrence, so HBM sees only
Q/K/V/O (ref: the role of the reference's fused attention backends, e.g.
torch SDPA/FlashAttention used by release/train_tests LLM configs —
rebuilt here natively for the MXU rather than bound from a CUDA library).

Layout: q, k, v are [BH, T, D] (batch*heads folded — each program works
on one head).  Grid (BH, num_q_blocks, num_kv_blocks) with the kv axis
innermost and "arbitrary" semantics: per (bh, q-block) the kernel scans
kv blocks, maintaining running max/denominator (m, l) and an fp32
accumulator in VMEM scratch.  Causal blocks above the diagonal are
skipped (predicated off), the diagonal block is masked in-register.

Backward: custom_vjp with the standard two-kernel flash backward — a
dkv kernel (grid over kv blocks, scanning q) and a dq kernel (grid over
q blocks, scanning kv), both recomputing P from the saved row-wise
log-sum-exp instead of reading a stored score matrix.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _causal_mask(qi, ki, bq, bk):
    """(bq, bk) bool mask for the (qi, ki) block pair: row >= col."""
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


# --------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, bq, bk, causal):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: skip kv blocks strictly above the diagonal.
    visit = (ki * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(visit)
    def _compute():
        q = q_ref[0]                      # (bq, d) bf16
        k = k_ref[0]                      # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (bq, bk)
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk), s, _NEG_INF)
        m_prev = m_scr[:, :1]                               # (bq, 1)
        row_max = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, row_max)
        p = jnp.exp(s - m_new)                              # (bq, bk) f32
        alpha = jnp.exp(m_prev - m_new)                     # (bq, 1)
        l_new = alpha * l_scr[:, :1] + \
            jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        inv = jnp.where(l > 0, 1.0 / jnp.where(l > 0, l, 1.0), 0.0)
        o_ref[0] = (acc_scr[...] * inv).astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30))
        # (bh, 8, t) layout: TPU blocks need sublane dims divisible by 8,
        # so the per-row lse is replicated across 8 sublanes.
        lse_ref[0] = jnp.broadcast_to(lse.reshape(1, -1),
                                      (8, lse.shape[0]))


def _flash_forward(q, k, v, *, scale, bq, bk, causal, interpret):
    bh, t, d = q.shape
    nq, nk = pl.cdiv(t, bq), pl.cdiv(t, bk)
    grid = (bh, nq, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, bq=bq, bk=bk,
                               causal=causal)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running denominator
            pltpu.VMEM((bq, d), jnp.float32),     # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# -------------------------------------------------------------- backward
def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, bq, bk, causal):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    visit = (qi * bq + bq - 1 >= ki * bk) if causal else True

    @pl.when(visit)
    def _compute():
        q = q_ref[0]                      # (bq, d)
        k = k_ref[0]                      # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (bq, bk)
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk), s, _NEG_INF)
        lse = lse_ref[0, :1, :].reshape(-1, 1)               # (bq, 1)
        p = jnp.exp(s - lse)                                 # (bq, bk)
        do = do_ref[0]                                       # (bq, d)
        # dv += P^T @ dO
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dS = P * (dO @ V^T - delta)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, bk)
        delta = delta_ref[0, :1, :].reshape(-1, 1)           # (bq, 1)
        ds = p * (dp - delta)                                # (bq, bk)
        # dK += dS^T @ Q * scale
        dk_scr[...] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, scale, bq, bk, causal):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    visit = (ki * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(visit)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk), s, _NEG_INF)
        lse = lse_ref[0, :1, :].reshape(-1, 1)
        p = jnp.exp(s - lse)
        do = do_ref[0]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        delta = delta_ref[0, :1, :].reshape(-1, 1)
        ds = p * (dp - delta)
        dq_scr[...] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _flash_backward(res, g, *, scale, bq, bk, causal, interpret,
                    dlse=None):
    q, k, v, out, lse = res
    do = g
    bh, t, d = q.shape
    # delta_i = rowsum(dO_i * O_i) — cheap, fused by XLA.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                  # (bh, t)
    if dlse is not None:
        # Cotangent flowing into the exposed log-sum-exp output (ring
        # attention's merge weights): d(lse_i)/d(s_ij) = p_ij, so the
        # per-row dlse term enters ds = p*(dp - delta + dlse) — i.e.
        # exactly like delta with opposite sign.  Fold it in here so
        # the two backward kernels need no changes.
        delta = delta - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[:, None, :], lse.shape)    # (bh, 8, t)
    nq, nk = pl.cdiv(t, bq), pl.cdiv(t, bk)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, bq=bq, bk=bk,
                          causal=causal),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 8, bq), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, bq=bq, bk=bk,
                          causal=causal),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------- public op
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhtd(q, k, v, scale, bq, bk, causal, interpret):
    out, _ = _flash_forward(q, k, v, scale=scale, bq=bq, bk=bk,
                            causal=causal, interpret=interpret)
    return out


def _flash_bhtd_fwd(q, k, v, scale, bq, bk, causal, interpret):
    out, lse = _flash_forward(q, k, v, scale=scale, bq=bq, bk=bk,
                              causal=causal, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bhtd_bwd(scale, bq, bk, causal, interpret, res, g):
    return _flash_backward(res, g, scale=scale, bq=bq, bk=bk,
                           causal=causal, interpret=interpret)


_flash_bhtd.defvjp(_flash_bhtd_fwd, _flash_bhtd_bwd)


# ------------------------------------------- partial (lse-exposing) op
# Same kernels, but the row-wise log-sum-exp is a real (differentiable)
# output: ring attention merges per-ring-step partial outputs with
# lse-derived weights (see parallel/ring_attention.py).
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhtd_lse(q, k, v, scale, bq, bk, causal, interpret):
    out, lse = _flash_forward(q, k, v, scale=scale, bq=bq, bk=bk,
                              causal=causal, interpret=interpret)
    return out, lse[:, 0, :]


def _flash_bhtd_lse_fwd(q, k, v, scale, bq, bk, causal, interpret):
    out, lse = _flash_forward(q, k, v, scale=scale, bq=bq, bk=bk,
                              causal=causal, interpret=interpret)
    return (out, lse[:, 0, :]), (q, k, v, out, lse)


def _flash_bhtd_lse_bwd(scale, bq, bk, causal, interpret, res, g):
    do, dlse = g
    return _flash_backward(res, do, scale=scale, bq=bq, bk=bk,
                           causal=causal, interpret=interpret,
                           dlse=dlse)


_flash_bhtd_lse.defvjp(_flash_bhtd_lse_fwd, _flash_bhtd_lse_bwd)


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             block_q: int = 256, block_k: int = 256,
                             interpret: bool | None = None):
    """Flash attention that also returns the row log-sum-exp.

    q, k, v: [B, T, H, D] -> (out [B, T, H, D], lse [B, T, H] fp32).
    The lse output is differentiable (its cotangent folds into the
    backward's delta term), which makes this the building block for
    blockwise/ring attention merges."""
    b, t, h, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"seq len {t} must divide block sizes "
                         f"({block_q}, {block_k})")
    scale = d ** -0.5

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out, lse = _flash_bhtd_lse(fold(q), fold(k), fold(v), scale,
                               block_q, block_k, causal, interpret)
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, t).transpose(0, 2, 1)
    return out, lse


def flash_attention(q, k, v, *, causal: bool = True,
                    block_q: int = 256, block_k: int = 256,
                    interpret: bool | None = None):
    """Causal flash attention.  q, k, v: [B, T, H, D] -> [B, T, H, D].

    ``interpret=None`` auto-selects: compiled kernel on TPU, pallas
    interpreter elsewhere (so CPU-mesh tests exercise the same code).
    Block sizes must keep T % block == 0 (pretraining shapes are
    128-multiples; assert early rather than mask the tail).
    """
    b, t, h, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"seq len {t} must divide block sizes "
                         f"({block_q}, {block_k})")
    scale = d ** -0.5

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out = _flash_bhtd(fold(q), fold(k), fold(v), scale, block_q, block_k,
                      causal, interpret)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
