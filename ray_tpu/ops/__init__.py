"""TPU kernels (pallas) for the hot ops.

The compute path of the framework is XLA-compiled jax; these kernels
cover the places where XLA's fusion leaves HBM bandwidth on the table —
first of all attention, whose materialized [B,H,T,T] score matrix
dominates memory traffic at pretraining shapes.
"""

from .flash_attention import flash_attention  # noqa: F401
