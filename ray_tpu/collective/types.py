"""Collective types and backend registry.

Role-equivalent to the reference's ray.util.collective.types (ref:
python/ray/util/collective/types.py:29-44 Backend enum that validates
NCCL/GLOO and rejects MPI).  The TPU build ships:

- ``Backend.XLA`` — jax collectives over the device mesh (ICI within a
  slice, DCN across slices via jax.distributed) — the NCCL replacement.
- ``Backend.CPU`` — host TCP collectives for control-plane tensors — the
  GLOO replacement.

NCCL is rejected by name with a pointer to XLA, the mirror image of the
reference rejecting MPI.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class Backend(str, enum.Enum):
    XLA = "xla"
    CPU = "cpu"

    @classmethod
    def parse(cls, name: str) -> "Backend":
        low = str(name).lower()
        if low in ("xla", "tpu", "jax"):
            return cls.XLA
        if low in ("cpu", "host", "gloo"):
            return cls.CPU
        if low in ("nccl", "cuda"):
            raise ValueError(
                "NCCL is a CUDA-only backend; this framework is TPU-native "
                "— use backend='xla' for device collectives over ICI.")
        raise ValueError(f"Unknown collective backend {name!r}")


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "prod"
    MAX = "max"
    MIN = "min"
    MEAN = "mean"


@dataclass
class GroupInfo:
    group_name: str
    world_size: int
    rank: int
    backend: Backend
