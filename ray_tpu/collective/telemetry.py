"""Collective-op telemetry shared by the CPU and XLA backends.

Every eager collective records (op, backend, group size, payload bytes,
latency) into the process-local metrics registry:

  rt_collective_latency_seconds{op,backend,world}   latency histogram
  rt_collective_bus_bandwidth_bytes_per_sec{op,backend}
                                                    effective bus BW

Bus bandwidth uses the standard nccl-tests algbw→busbw factors so
numbers are comparable across ops and group sizes (allreduce moves
2(n-1)/n of the payload per link, allgather/reducescatter (n-1)/n,
broadcast/p2p 1).  Snapshots ride the existing worker heartbeat; the
op is also appended to the flight recorder ring so a postmortem shows
which collective a dead worker was in.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

# Latency boundaries tuned for collectives: 100µs .. 30s.
_BOUNDS = (1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
           30.0)

_BUSBW_FACTOR = {
    "allreduce": lambda n: 2.0 * (n - 1) / n,
    "reducescatter": lambda n: (n - 1) / n,
    "allgather": lambda n: (n - 1) / n,
    "broadcast": lambda n: 1.0,
    "barrier": lambda n: 0.0,
    "send": lambda n: 1.0,
    "recv": lambda n: 1.0,
}


# ---------------------------------------------------- gang watchdog
# Entry stamps for the collective-entry watchdog: each rank stamps
# "I am inside op #seq of group G" on entry and clears it on exit.
# The worker flush loop ships the CURRENT inflight set to the
# controller every tick, which merges stamps across ranks; `rt
# doctor` flags gangs where some ranks are absent past the
# collective_watchdog_s deadline — naming the op AND the missing
# ranks, the diagnosis that previously required reading every rank's
# log by hand.
_inflight_lock = threading.Lock()
_inflight: Dict[Tuple[str, int], Dict[str, Any]] = {}


def _stamp_entry(op: str, backend: str, world_size: int,
                 group_name: str, rank: int, seq: int) -> None:
    with _inflight_lock:
        _inflight[(group_name, seq)] = {
            "group": group_name, "seq": int(seq), "op": op,
            "backend": backend, "world": int(world_size),
            "rank": int(rank), "since": time.time()}


def _stamp_exit(group_name: str, seq: int) -> None:
    with _inflight_lock:
        _inflight.pop((group_name, seq), None)


def inflight_entries() -> List[Dict[str, Any]]:
    """Snapshot of collectives this process is currently inside.
    Each entry carries ``age_s`` (a same-clock delta) so the
    controller can rebase the entry time onto ITS clock — absolute
    worker-host timestamps are not comparable across hosts."""
    now = time.time()
    with _inflight_lock:
        return [{**v, "age_s": max(now - v["since"], 0.0)}
                for v in _inflight.values()]


def record_op(op: str, backend: str, world_size: int, nbytes: int,
              seconds: float) -> None:
    try:
        from ..util import flight_recorder
        from ..util.metrics import Gauge, Histogram

        tags = {"op": op, "backend": backend, "world": str(world_size)}
        Histogram("rt_collective_latency_seconds",
                  "Eager collective op latency.",
                  boundaries=_BOUNDS,
                  tag_keys=("op", "backend", "world")).observe(
            seconds, tags=tags)
        factor = _BUSBW_FACTOR.get(op, lambda n: 1.0)(
            max(world_size, 1))
        if nbytes > 0 and seconds > 0 and factor > 0:
            # Same tag set as the histogram: groups of different sizes
            # must not overwrite one another's series.
            Gauge("rt_collective_bus_bandwidth_bytes_per_sec",
                  "Effective bus bandwidth of the last collective "
                  "(nccl-tests busbw convention).",
                  tag_keys=("op", "backend", "world")).set(
                nbytes * factor / seconds, tags=tags)
        flight_recorder.record("collective", op=op, backend=backend,
                               world=world_size, bytes=nbytes,
                               seconds=round(seconds, 6))
    except Exception:
        pass  # telemetry must never fail a collective


def _record_span(op: str, backend: str, world_size: int,
                 t0_wall: float, error: str = "") -> None:
    """Timeline span for one collective, tagged op/backend/world — the
    cluster timeline shows WHICH collective a rank sat in, not just the
    latency histogram the metrics carry."""
    try:
        from ..util import spans

        tags = {"op": op, "backend": backend, "world": str(world_size)}
        if error:
            tags["error"] = error
        spans.record_span(op, t0_wall, time.time(), cat="collective",
                          tags=tags)
    except Exception:
        pass


@contextmanager
def timed_op(op: str, backend: str, world_size: int, nbytes: int = 0,
             *, group_name: Optional[str] = None,
             rank: Optional[int] = None, seq: Optional[int] = None):
    # Flight-record the START too: a worker preempted mid-collective
    # must show WHICH op it was blocked in — completion-only records
    # would miss exactly the hung/preempted case postmortems exist for.
    try:
        from ..util import flight_recorder

        flight_recorder.record("collective_begin", op=op,
                               backend=backend, world=world_size,
                               bytes=nbytes)
    except Exception:
        flight_recorder = None
    stamped = group_name is not None and rank is not None \
        and seq is not None
    if stamped:
        _stamp_entry(op, backend, world_size, group_name, rank, seq)
    t0 = time.perf_counter()
    t0_wall = time.time()
    try:
        yield
    except BaseException as e:
        if flight_recorder is not None:
            flight_recorder.record(
                "collective_error", op=op, error=repr(e),
                seconds=round(time.perf_counter() - t0, 6))
        _record_span(op, backend, world_size, t0_wall, error=repr(e))
        raise
    finally:
        if stamped:
            _stamp_exit(group_name, seq)
    record_op(op, backend, world_size, nbytes,
              time.perf_counter() - t0)
    _record_span(op, backend, world_size, t0_wall)
