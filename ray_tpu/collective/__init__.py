"""Public collective API — ``ray_tpu.collective``.

Role-equivalent to the reference's ray.util.collective surface (ref:
python/ray/util/collective/collective.py:40 GroupManager, :120
init_collective_group, :151 declarative create_collective_group via a
named Info store, :258 allreduce and friends), redesigned for TPU:

- ``backend="xla"`` (the NCCL replacement) bootstraps jax.distributed
  across the member processes and exposes BOTH eager host collectives
  and ``get_group(...).global_mesh()`` — the in-graph path where
  collectives are mesh axes (psum/all_gather inside jit) riding ICI.
- ``backend="cpu"`` (the GLOO replacement) is a host TCP group for
  control-plane tensors.

Rendezvous rides the controller KV instead of a detached named actor:
members publish/poll ``col/<group>/...`` keys.  Deviation from the
reference: collectives here are FUNCTIONAL — they return the result
array rather than mutating the input in place (jax arrays are
immutable; in-place mutation is a torch idiom).

Consumers: IMPALA learner weight sync (ray_tpu.rl.impala) and the Train
JaxBackend gang bootstrap (ray_tpu.train.backend).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

from .types import Backend, GroupInfo, ReduceOp

__all__ = [
    "Backend", "ReduceOp", "GroupInfo", "GroupManager",
    "init_collective_group", "create_collective_group",
    "is_group_initialized", "destroy_collective_group", "get_group",
    "get_rank", "get_collective_group_size", "allreduce", "allgather",
    "reducescatter", "broadcast", "barrier", "send", "recv",
]

logger = logging.getLogger("ray_tpu.collective")

_DECL_PREFIX = "col/decl/"          # declarative group info in the KV


class KVStore:
    """Rendezvous store over the controller KV (the named-Info-actor
    pattern, ref: collective.py:151, replayed onto the GCS-equivalent).

    Backends call set(key, str)/get(key) -> str|None; keys are
    namespaced ``col/<group>/...`` by the callers."""

    def __init__(self):
        from ray_tpu.core import runtime as _rt

        self._rt = _rt.get_runtime()
        if not hasattr(self._rt, "controller_call"):
            raise RuntimeError(
                "collective groups need the cluster runtime "
                "(init(mode='cluster') or a connected worker); "
                "local mode has no controller KV")

    def set(self, key: str, value: str) -> None:
        self._rt.controller_call(
            "kv_put", {"key": key, "value": value.encode()})

    def get(self, key: str) -> Optional[str]:
        raw = self._rt.controller_call("kv_get", {"key": key})
        return raw.decode() if raw is not None else None

    def delete(self, key: str) -> None:
        self._rt.controller_call("kv_del", {"key": key})


class GroupManager:
    """Per-process registry of collective-group memberships (ref:
    collective.py:40 — one instance per process, a process may belong
    to many groups)."""

    def __init__(self):
        self._groups: Dict[str, Any] = {}
        self._infos: Dict[str, GroupInfo] = {}

    def create_collective_group(self, backend, world_size: int,
                                rank: int, group_name: str):
        backend = Backend.parse(backend)
        store = KVStore()
        if backend == Backend.CPU:
            from .collective_group.cpu_group import CPUGroup

            g = CPUGroup(group_name, world_size, rank, store)
        else:
            from .collective_group.xla_group import XLAGroup

            g = XLAGroup(group_name, world_size, rank, store)
        self._groups[group_name] = g
        self._infos[group_name] = GroupInfo(group_name, world_size,
                                            rank, backend)
        return g

    def is_group_exist(self, group_name: str) -> bool:
        return group_name in self._groups

    def get_group_by_name(self, group_name: str):
        return self._groups.get(group_name)

    def destroy_collective_group(self, group_name: str) -> None:
        g = self._groups.pop(group_name, None)
        self._infos.pop(group_name, None)
        if g is not None:
            g.destroy()


_group_mgr = GroupManager()


def is_group_initialized(group_name: str = "default") -> bool:
    """True if THIS process already joined ``group_name``."""
    return _group_mgr.is_group_exist(group_name)


def init_collective_group(world_size: int, rank: int,
                          backend: str = "cpu",
                          group_name: str = "default"):
    """Join a collective group from inside a worker/actor process (ref:
    collective.py:120).  Blocks until all ``world_size`` members have
    rendezvoused.  Returns the group handle."""
    if not group_name:
        raise ValueError("group_name must be a non-empty string")
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} outside [0, {world_size})")
    if _group_mgr.is_group_exist(group_name):
        raise RuntimeError(
            f"group {group_name!r} already initialized in this process")
    return _group_mgr.create_collective_group(backend, world_size, rank,
                                              group_name)


def create_collective_group(actors: Sequence[Any], world_size: int,
                            ranks: Sequence[int],
                            backend: str = "cpu",
                            group_name: str = "default") -> None:
    """Declare a list of actors as a collective group, from the DRIVER
    (ref: collective.py:146).  Membership info is stored in the
    controller KV; each actor lazily joins on its first collective call
    (looked up by its own actor id)."""
    backend = Backend.parse(backend)
    if len(ranks) != len(actors) or world_size != len(actors):
        raise ValueError(
            f"need one rank per actor and world_size == len(actors); "
            f"got {len(actors)} actors, {len(ranks)} ranks, "
            f"world_size={world_size}")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(
            f"ranks must be a permutation of 0..{world_size - 1}, "
            f"got {list(ranks)}")
    import json

    store = KVStore()
    key = _DECL_PREFIX + group_name
    if store.get(key) is not None:
        raise RuntimeError(f"group {group_name!r} already declared")
    info = {"backend": backend.value, "world_size": world_size,
            "ranks": {a.actor_id.hex(): int(r)
                      for a, r in zip(actors, ranks)}}
    store.set(key, json.dumps(info))


def _lazy_join(group_name: str):
    """Inside an actor: join a driver-declared group by looking up this
    actor's rank in the KV declaration (ref: collective.py
    _check_and_get_group's lazy init through the Info actor)."""
    import json

    import ray_tpu

    store = KVStore()
    raw = store.get(_DECL_PREFIX + group_name)
    if raw is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in "
            f"this process and was never declared via "
            f"create_collective_group()")
    info = json.loads(raw)
    my_id = ray_tpu.get_runtime_context().get_actor_id()
    if my_id is None or my_id not in info["ranks"]:
        raise RuntimeError(
            f"this process (actor {my_id}) is not a member of "
            f"collective group {group_name!r}")
    return _group_mgr.create_collective_group(
        info["backend"], info["world_size"], info["ranks"][my_id],
        group_name)


def _get(group_name: str):
    g = _group_mgr.get_group_by_name(group_name)
    if g is None:
        g = _lazy_join(group_name)
    return g


def get_group(group_name: str = "default"):
    """The group handle (e.g. for ``global_mesh()`` on XLA groups)."""
    return _get(group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down this process's membership AND the group's rendezvous
    state in the KV (declaration + rank addresses), so the name can be
    reused — the analogue of the reference killing the Info actor
    (ref: collective.py:100-107).  Call from every member (or the
    declaring driver) once the group is done."""
    _group_mgr.destroy_collective_group(group_name)
    try:
        store = KVStore()
        from ray_tpu.core import runtime as _rt

        rt = _rt.get_runtime()
        # Exact key for the declaration (a prefix scan would also hit
        # 'train2' when destroying 'train'); the rank-address prefix
        # ends with '/' so it is collision-safe.
        store.delete(_DECL_PREFIX + group_name)
        for key in rt.controller_call(
                "kv_keys", {"prefix": f"col/{group_name}/"}):
            store.delete(key)
    except Exception:
        logger.debug("KV cleanup for group %r failed", group_name,
                     exc_info=True)


def get_rank(group_name: str = "default") -> int:
    """This process's rank in the group; -1 if not a member (ref:
    collective.py:223)."""
    g = _group_mgr.get_group_by_name(group_name)
    return g.rank if g is not None else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _group_mgr.get_group_by_name(group_name)
    return g.world_size if g is not None else -1


# ------------------------------------------------------------------ ops
def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    """All-reduce across the group; RETURNS the reduced array (ref:
    collective.py:258 — functional here, see module docstring)."""
    return _get(group_name).allreduce(tensor, op)


def allgather(tensor, group_name: str = "default") -> List[Any]:
    """Gather every rank's tensor; returns the rank-ordered list."""
    return _get(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    """Reduce then return this rank's axis-0 shard."""
    return _get(group_name).reducescatter(tensor, op)


def broadcast(tensor, src_rank: int = 0,
              group_name: str = "default"):
    """Broadcast ``src_rank``'s tensor; returns it on every rank."""
    return _get(group_name).broadcast(tensor, src_rank)


def barrier(group_name: str = "default") -> None:
    _get(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send (CPU backend; XLA p2p is in-graph ppermute)."""
    _get(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default",
         timeout: float = 120.0):
    """Blocking point-to-point receive from ``src_rank``."""
    return _get(group_name).recv(src_rank, timeout=timeout)
