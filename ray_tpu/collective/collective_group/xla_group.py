"""XLA device collective group — the NCCL replacement for TPU.

Role-equivalent to the reference's nccl_collective_group (ref:
python/ray/util/collective/collective_group/nccl_collective_group.py, with
unique-id rendezvous via a named actor at collective.py:151), redesigned
for the TPU execution model: instead of driving a communicator per tensor,
the group bootstraps ``jax.distributed`` across the member processes
(coordinator address exchanged through the rendezvous store) and exposes

- eager host-level collectives (this file) for control tensors and
  weight sync — compiled jax programs over the global device mesh; and
- the *in-graph* path: ``global_mesh()`` hands the caller a
  jax.sharding.Mesh spanning every member's chips, so training steps
  express collectives as mesh axes (psum/all_gather inside pjit) riding
  ICI — the actual TPU hot path (see ray_tpu.parallel).

One jax.distributed world per process: every XLA group in a process must
agree on (world_size, rank); the first initializes, later ones attach.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import numpy as np

from .. import telemetry as _telemetry
from ..types import ReduceOp

_initialized_world = None  # (world_size, rank) after jax.distributed init


def _ensure_jax_world(store, group_name: str, world_size: int,
                      rank: int) -> None:
    global _initialized_world
    if _initialized_world is not None:
        if _initialized_world != (world_size, rank):
            raise RuntimeError(
                f"jax.distributed already initialized as "
                f"{_initialized_world}, group {group_name!r} wants "
                f"{(world_size, rank)}")
        return
    import jax

    if world_size == 1:
        _initialized_world = (1, 0)
        return
    # Multi-process CPU worlds (the CI backend) need the CPU client
    # created WITH a cross-process collectives implementation, or every
    # computation spanning processes fails with "Multiprocess
    # computations aren't implemented on the CPU backend".  gloo is
    # compiled into jaxlib; the flag only affects CPU client creation,
    # so it is harmless on TPU.  Must happen before the first backend
    # touch — the client is built lazily on first jax.devices().
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jaxlib without the flag: CPU stays single-process
    key = f"col/{group_name}/coordinator"
    # Entry-stamped as gang op #0 (the regular collectives start at
    # seq 1): while a rank sits inside the rendezvous — waiting for
    # the coordinator address, or blocked in jax.distributed.initialize
    # on peers that never arrived — the worker flush loop ships the
    # stamp, and `rt doctor`'s find_distributed_init_stall names the
    # missing ranks once RT_DIST_INIT_TIMEOUT_S passes.
    with _telemetry.timed_op("distributed_init", "xla", world_size,
                             group_name=group_name, rank=rank,
                             seq=0):
        if rank == 0:
            import socket

            from ray_tpu.core.net import get_node_ip_address

            s = socket.socket()
            s.bind(("", 0))
            port = s.getsockname()[1]
            s.close()
            coord = f"{get_node_ip_address()}:{port}"
            store.set(key, coord)
        else:
            deadline = time.time() + 120
            while True:
                coord = store.get(key)
                if coord:
                    break
                if time.time() > deadline:
                    raise TimeoutError(
                        "coordinator address never appeared")
                time.sleep(0.02)
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=world_size,
                                   process_id=rank)
    _initialized_world = (world_size, rank)


class XLAGroup:
    def __init__(self, group_name: str, world_size: int, rank: int, store):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        _ensure_jax_world(store, group_name, world_size, rank)
        import jax

        self._jax = jax
        self.devices = jax.devices()  # global across member processes
        # Gang-op sequence for the collective-entry watchdog (same
        # SPMD lockstep contract as the cpu backend).
        self._gang_seq = 0

    def _gang_op(self, op: str, nbytes: int = 0):
        self._gang_seq += 1
        return _telemetry.timed_op(op, "xla", self.world_size, nbytes,
                                   group_name=self.group_name,
                                   rank=self.rank, seq=self._gang_seq)

    # ------------------------------------------------------------ in-graph
    def global_mesh(self, axis_name: str = "x"):
        """A 1-D mesh over every device in the group — the handle training
        code uses to express collectives as sharding axes (the TPU hot
        path; eager ops below are for control tensors)."""
        from jax.sharding import Mesh

        return Mesh(np.array(self.devices), (axis_name,))

    # -------------------------------------------------------------- eager
    def _gather_all(self, array: np.ndarray) -> List[np.ndarray]:
        from jax.experimental import multihost_utils

        stacked = multihost_utils.process_allgather(np.asarray(array))
        return [np.asarray(s) for s in stacked]

    def _allreduce(self, arr: np.ndarray, op: ReduceOp) -> np.ndarray:
        """Untimed core — reducescatter composes on this so the
        composite op records ONE telemetry sample."""
        parts = self._gather_all(arr)
        out = np.array(parts[0], copy=True)
        for p in parts[1:]:
            if op in (ReduceOp.SUM, ReduceOp.MEAN):
                out += p
            elif op == ReduceOp.PRODUCT:
                out *= p
            elif op == ReduceOp.MAX:
                np.maximum(out, p, out=out)
            elif op == ReduceOp.MIN:
                np.minimum(out, p, out=out)
        if op == ReduceOp.MEAN:
            out = out / len(parts)
        return out

    def allreduce(self, array, op: ReduceOp = ReduceOp.SUM):
        arr = np.asarray(array)
        with self._gang_op("allreduce", arr.nbytes):
            return self._allreduce(arr, op)

    def allgather(self, array) -> List[np.ndarray]:
        arr = np.asarray(array)
        with self._gang_op("allgather", arr.nbytes):
            return self._gather_all(arr)

    def reducescatter(self, array, op: ReduceOp = ReduceOp.SUM):
        arr = np.asarray(array)
        with self._gang_op("reducescatter", arr.nbytes):
            total = self._allreduce(arr, op)
            return np.array_split(total, self.world_size,
                                  axis=0)[self.rank]

    def broadcast(self, array, src_rank: int = 0):
        from jax.experimental import multihost_utils

        arr = np.asarray(array)
        with self._gang_op("broadcast", arr.nbytes):
            return np.asarray(multihost_utils.broadcast_one_to_all(
                arr, is_source=self.rank == src_rank))

    def barrier(self) -> None:
        from jax.experimental import multihost_utils

        with self._gang_op("barrier"):
            multihost_utils.sync_global_devices(
                f"rt_barrier_{self.group_name}")

    def send(self, array, dst_rank: int) -> None:
        raise NotImplementedError(
            "point-to-point on the XLA backend is expressed in-graph via "
            "ppermute over a mesh axis (see ray_tpu.parallel); use the "
            "cpu backend for host p2p")

    def recv(self, src_rank: int, timeout: float = 120.0):
        raise NotImplementedError(
            "point-to-point on the XLA backend is expressed in-graph via "
            "ppermute over a mesh axis (see ray_tpu.parallel); use the "
            "cpu backend for host p2p")

    def destroy(self) -> None:
        pass  # the jax world outlives groups by design
