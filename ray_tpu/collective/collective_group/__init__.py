"""Collective backend implementations (CPU host TCP, XLA device mesh)."""
