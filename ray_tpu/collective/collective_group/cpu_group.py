"""Host (CPU) collective group over TCP — the GLOO-equivalent backend.

Role-equivalent to the reference's gloo_collective_group (ref:
python/ray/util/collective/collective_group/gloo_collective_group.py):
control-plane tensor collectives between processes that do not need the
device plane.  Topology: rank 0 is the hub for reductions/broadcasts
(star), point-to-point send/recv is direct.  All ranks must issue the
same sequence of collective calls (SPMD discipline), so ops need no tags
— sockets deliver them in lockstep order.
"""

from __future__ import annotations

import pickle
import queue
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import telemetry as _telemetry
from ..types import ReduceOp

_LEN = struct.Struct("<Q")


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=5)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(header)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("collective peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _reduce(arrays: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    out = np.array(arrays[0], copy=True)
    for a in arrays[1:]:
        if op in (ReduceOp.SUM, ReduceOp.MEAN):
            out += a
        elif op == ReduceOp.PRODUCT:
            out *= a
        elif op == ReduceOp.MAX:
            np.maximum(out, a, out=out)
        elif op == ReduceOp.MIN:
            np.minimum(out, a, out=out)
    if op == ReduceOp.MEAN:
        out = out / len(arrays)
    return out


class CPUGroup:
    """One rank's membership in a named host collective group."""

    def __init__(self, group_name: str, world_size: int, rank: int,
                 store):
        """``store`` is a rendezvous KV with set(key, value) / get(key)
        (the named-actor pattern, ref: collective.py:151 creating the
        "Info" actor)."""
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._store = store
        # Gang-op sequence number for the collective-entry watchdog:
        # SPMD discipline means every rank issues the same gang ops in
        # the same order, so op #N lines up across ranks (p2p send/recv
        # are pairwise, not gang-wide, and do not advance it).
        self._gang_seq = 0
        from ray_tpu.core.net import get_node_ip_address

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Bind only the advertised interface (frames are pickled — same
        # trust model and rationale as RpcServer's single-interface bind).
        try:
            self._listener.bind((get_node_ip_address(), 0))
        except OSError:
            self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(world_size + 4)
        self._port = self._listener.getsockname()[1]
        self._peers: Dict[int, socket.socket] = {}
        self._p2p_in: Dict[int, "queue.Queue[Any]"] = {}
        self._p2p_lock = threading.Lock()
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        store.set(f"col/{group_name}/{rank}",
                  f"{get_node_ip_address()}:{self._port}")
        if rank == 0:
            self._await_hub_connections()
        else:
            self._hub = self._dial(0)

    # ---------------------------------------------------------- connections
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            hello = _recv_msg(conn)
            peer_rank = hello["rank"]
            kind = hello["kind"]
            if kind == "hub":
                self._peers[peer_rank] = conn
            else:  # p2p inbound: pump into a queue per source
                q = self._p2p_queue(peer_rank)
                t = threading.Thread(target=self._pump, args=(conn, q),
                                     daemon=True)
                t.start()

    def _pump(self, conn: socket.socket, q: "queue.Queue[Any]") -> None:
        try:
            while True:
                q.put(_recv_msg(conn))
        except (ConnectionError, OSError):
            pass

    def _p2p_queue(self, peer: int) -> "queue.Queue[Any]":
        with self._p2p_lock:
            q = self._p2p_in.get(peer)
            if q is None:
                q = self._p2p_in[peer] = queue.Queue()
            return q

    def _peer_addr(self, rank: int, timeout: float = 60.0) -> str:
        deadline = time.time() + timeout
        key = f"col/{self.group_name}/{rank}"
        while time.time() < deadline:
            addr = self._store.get(key)
            if addr:
                return addr
            time.sleep(0.02)
        raise TimeoutError(f"rank {rank} never registered in group "
                           f"{self.group_name!r}")

    def _dial(self, rank: int, kind: str = "hub") -> socket.socket:
        host, port = self._peer_addr(rank).rsplit(":", 1)
        deadline = time.time() + 60
        while True:
            try:
                sock = socket.create_connection((host, int(port)), timeout=10)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_msg(sock, {"rank": self.rank, "kind": kind})
        return sock

    def _await_hub_connections(self, timeout: float = 120.0) -> None:
        deadline = time.time() + timeout
        while len(self._peers) < self.world_size - 1:
            if time.time() > deadline:
                raise TimeoutError(
                    f"only {len(self._peers)}/{self.world_size - 1} peers "
                    f"joined group {self.group_name!r}")
            time.sleep(0.01)

    # ------------------------------------------------------------ ops (hub)
    def _allreduce(self, array: np.ndarray, op: ReduceOp) -> np.ndarray:
        """Untimed core — barrier/reducescatter compose on this so the
        composite op records ONE telemetry sample, not a nested bogus
        allreduce one."""
        if self.world_size == 1:
            return _reduce([array], op)
        if self.rank == 0:
            parts = [array]
            for r in range(1, self.world_size):
                parts.append(_recv_msg(self._peers[r]))
            out = _reduce(parts, op)
            for r in range(1, self.world_size):
                _send_msg(self._peers[r], out)
            return out
        _send_msg(self._hub, array)
        return _recv_msg(self._hub)

    def _gang_op(self, op: str, nbytes: int = 0):
        self._gang_seq += 1
        return _telemetry.timed_op(op, "cpu", self.world_size, nbytes,
                                   group_name=self.group_name,
                                   rank=self.rank, seq=self._gang_seq)

    def allreduce(self, array, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        array = np.asarray(array)
        with self._gang_op("allreduce", array.nbytes):
            return self._allreduce(array, op)

    def allgather(self, array) -> List[np.ndarray]:
        array = np.asarray(array)
        with self._gang_op("allgather", array.nbytes):
            if self.world_size == 1:
                return [array]
            if self.rank == 0:
                parts = [array] + [None] * (self.world_size - 1)
                for r in range(1, self.world_size):
                    parts[r] = _recv_msg(self._peers[r])
                for r in range(1, self.world_size):
                    _send_msg(self._peers[r], parts)
                return parts
            _send_msg(self._hub, array)
            return _recv_msg(self._hub)

    def reducescatter(self, array, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        """Reduce then return this rank's 1/world_size shard (axis 0)."""
        array = np.asarray(array)
        with self._gang_op("reducescatter", array.nbytes):
            total = self._allreduce(array, op)
            shards = np.array_split(total, self.world_size, axis=0)
            return shards[self.rank]

    def broadcast(self, array, src_rank: int = 0) -> np.ndarray:
        arr = np.asarray(array)
        with self._gang_op("broadcast", arr.nbytes):
            if self.world_size == 1:
                return arr
            if self.rank == 0:
                if src_rank == 0:
                    data = arr
                else:
                    data = _recv_msg(self._peers[src_rank])
                for r in range(1, self.world_size):
                    _send_msg(self._peers[r], data)
                return data
            if self.rank == src_rank:
                _send_msg(self._hub, arr)
            return _recv_msg(self._hub)

    def barrier(self) -> None:
        with self._gang_op("barrier"):
            self._allreduce(np.zeros(1, dtype=np.int8), ReduceOp.SUM)

    # ------------------------------------------------------------- ops (p2p)
    def send(self, array, dst_rank: int) -> None:
        sock = getattr(self, "_p2p_out", None)
        if sock is None:
            self._p2p_out: Dict[int, socket.socket] = {}
        conn = self._p2p_out.get(dst_rank)
        if conn is None:
            conn = self._p2p_out[dst_rank] = self._dial(dst_rank, "p2p")
        arr = np.asarray(array)
        with _telemetry.timed_op("send", "cpu", self.world_size,
                                 arr.nbytes):
            _send_msg(conn, arr)

    def recv(self, src_rank: int, timeout: float = 120.0) -> np.ndarray:
        with _telemetry.timed_op("recv", "cpu", self.world_size):
            return self._p2p_queue(src_rank).get(timeout=timeout)

    def destroy(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        for sock in list(self._peers.values()):
            try:
                sock.close()
            except OSError:
                pass
        hub = getattr(self, "_hub", None)
        if hub is not None:
            try:
                hub.close()
            except OSError:
                pass
        for conn in getattr(self, "_p2p_out", {}).values():
            try:
                conn.close()
            except OSError:
                pass
