"""ClientServer — the head-side rt:// listener and per-client relay.

Role-equivalent to the reference's client proxier (ref:
util/client/server/proxier.py ProxyManager: listens on one public
port, starts a SpecificServer per client, forwards that client's
traffic to it).  Here the forwarding is a raw byte relay of the framed
RPC protocol — the thin client speaks end-to-end with its session
host; the relay adds no protocol of its own.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
from typing import Optional

logger = logging.getLogger("ray_tpu.client.server")


class ClientServer:
    def __init__(self, controller_address: str, *,
                 host: Optional[str] = None, port: int = 0):
        self.controller_address = controller_address
        self._requested_port = port
        self._host = host
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: int = 0

    async def start(self) -> int:
        from ray_tpu.core.net import get_node_ip_address

        bind = self._host
        if bind is None:
            bind = ("0.0.0.0" if os.environ.get("RT_BIND_ALL") == "1"
                    else get_node_ip_address())
        self._server = await asyncio.start_server(
            self._handle, bind, self._requested_port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("rt:// client server listening on %s:%d", bind,
                    self.port)
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
            self._server = None

    async def _handle(self, creader: asyncio.StreamReader,
                      cwriter: asyncio.StreamWriter) -> None:
        """One client connection = one session-host process + a
        bidirectional byte relay (ref: proxier.py:119 SpecificServer
        startup + data forwarding)."""
        # The host must import ray_tpu exactly as this process does
        # (the server may run from a dev checkout not on the default
        # path).
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p]
            + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
               if p])
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-u", "-m", "ray_tpu.client.session_host",
            "--address", self.controller_address, env=env,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT)
        port = None
        try:
            deadline = asyncio.get_event_loop().time() + 60.0
            while asyncio.get_event_loop().time() < deadline:
                line = await asyncio.wait_for(proc.stdout.readline(),
                                              60.0)
                if not line:
                    break
                if line.startswith(b"RT_CLIENT_PORT="):
                    port = int(line.split(b"=", 1)[1])
                    break
            if port is None:
                raise RuntimeError(
                    "session host produced no RT_CLIENT_PORT trailer")
            from ray_tpu.core.rpc import spawn_task

            spawn_task(self._drain(proc.stdout))
            sreader, swriter = await asyncio.open_connection(
                "127.0.0.1", port)
        except Exception:
            logger.exception("session host startup failed")
            try:
                cwriter.close()
            except Exception:
                pass
            if proc.returncode is None:
                proc.terminate()
            return
        try:
            await asyncio.wait(
                [asyncio.ensure_future(self._pump(creader, swriter)),
                 asyncio.ensure_future(self._pump(sreader, cwriter))],
                return_when=asyncio.FIRST_COMPLETED)
        finally:
            for w in (cwriter, swriter):
                try:
                    w.close()
                except Exception:
                    pass
            # Closing the host-side socket fires the session host's
            # connection-lost exit; give it a moment, then make sure.
            try:
                await asyncio.wait_for(proc.wait(), 15.0)
            except asyncio.TimeoutError:
                proc.terminate()

    @staticmethod
    async def _pump(reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                chunk = await reader.read(256 * 1024)
                if not chunk:
                    return
                writer.write(chunk)
                await writer.drain()
        except (ConnectionError, OSError, asyncio.CancelledError):
            return

    @staticmethod
    async def _drain(stream: asyncio.StreamReader) -> None:
        """Keep the session host's stdout pipe from filling."""
        try:
            while True:
                line = await stream.readline()
                if not line:
                    return
                logger.debug("session-host: %s",
                             line.decode("utf-8", "replace").rstrip())
        except Exception:
            return


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--address", required=True)
    ap.add_argument("--port", type=int, default=10001)
    args = ap.parse_args(argv)
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    server = ClientServer(args.address, port=args.port)
    port = loop.run_until_complete(server.start())
    print(f"RT_CLIENT_SERVER_PORT={port}", flush=True)
    try:
        loop.run_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
