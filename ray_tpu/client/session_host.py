"""Session host — the server-side driver hosting ONE rt:// client.

Role-equivalent to the reference's SpecificServer (ref:
util/client/server/proxier.py:119 — one dedicated server process per
client so each client is a real, isolated driver with its own job).
The ClientServer spawns this process per connection and relays the
client's frames to it verbatim; handlers here replay the thin client's
BaseRuntime calls onto a real ClusterRuntime and pin returned
ObjectRefs until the client releases them.
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--address", required=True,
                    help="controller address of the cluster")
    args = ap.parse_args(argv)

    import ray_tpu
    from ray_tpu.core.object_ref import ObjectRef
    from ray_tpu.core.rpc import RpcServer

    rt = ray_tpu.init(address=args.address)

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    # Blocking runtime ops (get/wait can block for minutes) run here so
    # the RPC loop stays responsive to concurrent client requests.
    pool = ThreadPoolExecutor(max_workers=8,
                              thread_name_prefix="client-op")
    server = RpcServer(host="127.0.0.1")  # only the relay dials us
    exit_event = asyncio.Event()
    # Client-held refs: the session host IS the owner/borrower of every
    # object the client sees; pinning here keeps ref counting honest
    # until the client's ObjectRef.__del__ releases (ref:
    # util/client/server/server.py object id tracking).
    pins: Dict[Any, ObjectRef] = {}

    def _pin(ref: ObjectRef):
        pins[ref.id] = ref
        return ref.id

    def _ref_of(oid) -> ObjectRef:
        ref = pins.get(oid)
        return ref if ref is not None else ObjectRef(oid)

    async def _sync(fn, *a):
        return await loop.run_in_executor(pool, fn, *a)

    async def c_init(_p):
        return {"job_id": rt.job_id, "config_json": rt.config.to_json()}

    async def c_submit_task(p):
        out = await _sync(rt.submit_task, p["spec"])
        return {"oids": [_pin(r) for r in out]}

    async def c_create_actor(p):
        await _sync(rt.create_actor, p["spec"])
        return {"ok": True}

    async def c_submit_actor_task(p):
        out = await _sync(rt.submit_actor_task, p["spec"])
        return {"oids": [_pin(r) for r in out]}

    async def c_put(p):
        return {"oid": _pin(await _sync(rt.put, p["value"]))}

    async def c_get(p):
        values = await _sync(rt.get, [_ref_of(o) for o in p["oids"]],
                             p.get("timeout"))
        return {"values": values}

    async def c_wait(p):
        ready, _nr = await _sync(rt.wait,
                                 [_ref_of(o) for o in p["oids"]],
                                 p["num_returns"], p.get("timeout"),
                                 p.get("fetch_local", True))
        return {"ready": [r.id for r in ready]}

    async def c_kill_actor(p):
        await _sync(rt.kill_actor, p["actor_id"], p["no_restart"])
        return {"ok": True}

    async def c_cancel(p):
        await _sync(rt.cancel, _ref_of(p["oid"]), p["force"])
        return {"ok": True}

    async def c_get_named_actor(p):
        handle = await _sync(rt.get_named_actor, p["name"],
                             p.get("namespace", ""))
        return {"handle": handle}

    async def c_controller(p):
        return await _sync(rt.controller_call, p["method"],
                           p.get("payload"))

    async def c_agent(p):
        return await _sync(rt.agent_call, p["method"],
                           p.get("payload"))

    async def c_cluster_resources(_p):
        return await _sync(rt.cluster_resources)

    async def c_available_resources(_p):
        return await _sync(rt.available_resources)

    async def c_nodes(_p):
        return await _sync(rt.nodes)

    def c_release(p):  # notify — fire and forget
        for oid in p["oids"]:
            pins.pop(oid, None)

    def c_shutdown(_p):  # notify
        loop.call_soon_threadsafe(exit_event.set)

    for name, fn in list(locals().items()):
        if name.startswith("c_"):
            server.register(name, fn)
    # The relay holds exactly one connection to us; when the client
    # goes away (clean or not), this session's driver exits and its
    # job's refs release (ref: proxier.py cleanup on client drop).
    server.on_connection_lost(
        lambda _tag: loop.call_soon_threadsafe(exit_event.set))

    port = loop.run_until_complete(server.start(0))
    print(f"RT_CLIENT_PORT={port}", flush=True)
    loop.run_until_complete(exit_event.wait())
    loop.run_until_complete(server.stop())
    ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
