"""ray_tpu.client — the remote-driver ("rt://") stack.

Role-equivalent to the reference's Ray Client (ref:
python/ray/util/client/ARCHITECTURE.md + util/client/server/): a laptop
driver connects to the head over ONE connection with
``init(address="rt://host:port")`` and uses the full API surface —
tasks, actors, put/get/wait, named actors, kill/cancel — without being
routable from the cluster.  Topology mirrors the reference's
SpecificServer-per-client design: the head-side ClientServer accepts
the connection, spawns a dedicated session-host process (a REAL driver
inside the cluster), and relays bytes; the thin ClientRuntime replays
BaseRuntime operations over that link.
"""

from .runtime import ClientRuntime  # noqa: F401
from .server import ClientServer  # noqa: F401
