"""ClientRuntime — the thin rt:// driver runtime.

Role-equivalent to the reference's client-side Ray Client worker (ref:
util/client/worker.py Worker: every API call becomes a message over one
connection; the server-side driver owns all cluster state).  Because
the whole public API funnels through BaseRuntime, this class IS the
client: api.remote/get/put/wait/actors work unchanged on top of it —
specs built locally, shipped whole, replayed by the session host.

ID safety: the session host is a dedicated driver with its own job id
(one per client), and the client never generates ObjectIDs itself
except task-return ids derived from its own task counter — the same
uniqueness contract a normal driver has.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..core.config import RuntimeConfig
from ..core.object_ref import ObjectRef
from ..core.rpc import EventLoopThread, RemoteCallError, RpcClient
from ..core.runtime import BaseRuntime


class ClientRuntime(BaseRuntime):
    is_client = True

    def __init__(self, config: RuntimeConfig, address: str):
        self.io = EventLoopThread("rt-client-io")
        self._cli = RpcClient(address, tag="rt-client",
                              connect_timeout=30.0)
        self.io.run(self._cli.connect())
        hello = self._raw_call("c_init", {}, timeout=60.0)
        cfg = RuntimeConfig.from_json(hello["config_json"])
        super().__init__(cfg, job_id=hello["job_id"])
        self._ref_lock = threading.Lock()
        self._ref_counts: Dict[Any, int] = {}
        self._shutdown_flag = False

    # ------------------------------------------------------------ plumbing
    def _raw_call(self, method: str, payload: Any,
                  timeout: Optional[float] = None) -> Any:
        return self.io.run(self._cli.call(method, payload), timeout)

    def _call(self, method: str, payload: Any,
              timeout: Optional[float] = None) -> Any:
        """Call the session host; a handler-side exception re-raises
        here as its ORIGINAL type (incl. remote traceback text)."""
        try:
            return self._raw_call(method, payload, timeout)
        except RemoteCallError as e:
            raise e.cause from None

    # ------------------------------------------------------------- backend
    def submit_task(self, spec) -> List[ObjectRef]:
        r = self._call("c_submit_task", {"spec": spec})
        return [ObjectRef(o) for o in r["oids"]]

    def create_actor(self, spec) -> None:
        self._call("c_create_actor", {"spec": spec})

    def submit_actor_task(self, spec) -> List[ObjectRef]:
        r = self._call("c_submit_actor_task", {"spec": spec})
        return [ObjectRef(o) for o in r["oids"]]

    def put(self, value: Any) -> ObjectRef:
        return ObjectRef(self._call("c_put", {"value": value})["oid"])

    def get(self, refs: List[ObjectRef],
            timeout: Optional[float]) -> List[Any]:
        rpc_timeout = None if timeout is None else timeout + 60.0
        r = self._call("c_get", {"oids": [x.id for x in refs],
                                 "timeout": timeout}, rpc_timeout)
        return r["values"]

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        rpc_timeout = None if timeout is None else timeout + 60.0
        r = self._call("c_wait", {
            "oids": [x.id for x in refs], "num_returns": num_returns,
            "timeout": timeout, "fetch_local": fetch_local},
            rpc_timeout)
        ready_ids = set(r["ready"])
        ready = [x for x in refs if x.id in ready_ids]
        not_ready = [x for x in refs if x.id not in ready_ids]
        return ready, not_ready

    def kill_actor(self, actor_id, no_restart: bool) -> None:
        self._call("c_kill_actor", {"actor_id": actor_id,
                                    "no_restart": no_restart})

    def cancel(self, ref: ObjectRef, force: bool) -> None:
        self._call("c_cancel", {"oid": ref.id, "force": force})

    def get_named_actor(self, name: str, namespace: str = ""):
        r = self._call("c_get_named_actor",
                       {"name": name, "namespace": namespace})
        return r["handle"]

    def controller_call(self, method: str, payload=None,
                        timeout: Optional[float] = None):
        return self._call("c_controller",
                          {"method": method, "payload": payload},
                          timeout)

    def agent_call(self, method: str, payload=None,
                   timeout: Optional[float] = None):
        """Reaches the session host's LOCAL node agent (head node)."""
        return self._call("c_agent",
                          {"method": method, "payload": payload},
                          timeout)

    def cluster_resources(self) -> Dict[str, float]:
        return self._call("c_cluster_resources", {})

    def available_resources(self) -> Dict[str, float]:
        return self._call("c_available_resources", {})

    def nodes(self) -> List[Dict[str, Any]]:
        return self._call("c_nodes", {})

    # ------------------------------------------------------- ref counting
    def add_local_ref(self, object_id) -> None:
        with self._ref_lock:
            self._ref_counts[object_id] = \
                self._ref_counts.get(object_id, 0) + 1

    def remove_local_ref(self, object_id) -> None:
        if self._shutdown_flag:
            return
        with self._ref_lock:
            n = self._ref_counts.get(object_id, 0) - 1
            if n > 0:
                self._ref_counts[object_id] = n
                return
            self._ref_counts.pop(object_id, None)
            if n < 0:
                return
        try:
            self.io.spawn(self._cli.notify("c_release",
                                           {"oids": [object_id]}))
        except Exception:
            pass  # interpreter teardown / link already gone

    # ------------------------------------------------------------ teardown
    def shutdown(self) -> None:
        if self._shutdown_flag:
            return
        self._shutdown_flag = True
        try:
            self.io.run(self._cli.notify("c_shutdown", {}),
                        timeout=5.0)
        except Exception:
            pass
        try:
            self.io.run(self._cli.close(), timeout=5.0)
        except Exception:
            pass
