"""Local-mode runtime: synchronous in-process execution.

Role-equivalent to the reference's local_mode (ref:
python/ray/_private/worker.py local mode paths): tasks run eagerly on
submission in the driver process, actors are plain instances.  Used for
debugging user code and as the executable spec of task semantics that the
cluster runtime must match (the test suite runs the same semantic tests
against both backends).
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from .errors import (ActorDiedError, ActorError, GetTimeoutError, TaskError)
from .ids import ActorID, ObjectID
from .object_ref import ObjectRef
from .runtime import BaseRuntime
from .task import ArgKind, TaskKind, TaskSpec


class _ActorSlot:
    __slots__ = ("instance", "lock", "dead", "class_name", "creation_error",
                 "registered_name")

    def __init__(self, instance, class_name: str):
        self.instance = instance
        self.lock = threading.Lock()
        self.dead = False
        self.class_name = class_name
        self.creation_error = None
        self.registered_name = None  # (namespace, name) if named


class LocalRuntime(BaseRuntime):
    def __init__(self, config, job_id=None):
        super().__init__(config, job_id)
        self._store: Dict[ObjectID, Any] = {}
        self._streams: Dict[str, Any] = {}
        self._actors: Dict[ActorID, _ActorSlot] = {}
        self._named: Dict[Tuple[str, str], Any] = {}
        self._func_cache: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # -- helpers ------------------------------------------------------------
    def _load_func(self, spec: TaskSpec):
        fn = self._func_cache.get(spec.func_id)
        if fn is None:
            fn = cloudpickle.loads(spec.func_blob)
            self._func_cache[spec.func_id] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec):
        vals = []
        for a in spec.args:
            if a.kind == ArgKind.OBJECT_REF:
                v = self._store.get(a.object_id, _MISSING)
                if v is _MISSING:
                    raise KeyError(f"Unknown object {a.object_id}")
                if isinstance(v, TaskError):
                    raise v
                vals.append(v)
            else:
                # Round-trip through pickle so local mode has the same
                # copy/isolation semantics as the cluster runtime.
                vals.append(pickle.loads(cloudpickle.dumps(a.value)))
        nkw = len(spec.kwargs_keys)
        if nkw:
            pos, kw_vals = vals[:-nkw], vals[-nkw:]
            kwargs = dict(zip(spec.kwargs_keys, kw_vals))
        else:
            pos, kwargs = vals, {}
        return pos, kwargs

    def _store_returns(self, spec: TaskSpec, result: Any) -> List[ObjectRef]:
        oids = spec.return_object_ids()
        if spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"Task {spec.display_name()} declared "
                    f"num_returns={spec.num_returns} but returned "
                    f"{len(values)} values")
        with self._lock:
            for oid, v in zip(oids, values):
                self._store[oid] = v
        return [ObjectRef(o) for o in oids]

    def _store_error(self, spec: TaskSpec, err: TaskError) -> List[ObjectRef]:
        oids = spec.return_object_ids()
        with self._lock:
            for oid in oids:
                self._store[oid] = err
        return [ObjectRef(o) for o in oids]

    def _run_in_task_context(self, spec: TaskSpec, fn, *args, **kwargs):
        prev = self._ctx.current_task_id
        self.set_current_task(spec.task_id)
        try:
            return fn(*args, **kwargs)
        finally:
            self.set_current_task(prev)

    # -- Runtime interface --------------------------------------------------
    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        if spec.is_streaming:
            return self._submit_streaming(spec)
        try:
            fn = self._load_func(spec)
            pos, kwargs = self._resolve_args(spec)
            result = self._run_in_task_context(spec, fn, *pos, **kwargs)
            return self._store_returns(spec, result)
        except BaseException as e:  # noqa: BLE001 — stored, raised at get()
            return self._store_error(spec, TaskError.from_exception(e))

    def _submit_streaming(self, spec: TaskSpec) -> List:
        """Local-mode generator task: items evaluate eagerly into the
        store; the returned ObjectRefGenerator drains a pre-completed
        stream (cluster mode streams incrementally)."""
        from .cluster_runtime import _StreamState
        from .object_ref import ObjectRefGenerator

        st = _StreamState()
        idx = 0
        try:
            fn = self._load_func(spec)
            pos, kwargs = self._resolve_args(spec)
            gen = self._run_in_task_context(spec, fn, *pos, **kwargs)
            for item in gen:
                idx += 1
                oid = ObjectID.for_task_return(spec.task_id, idx)
                with self._lock:
                    self._store[oid] = item
                st.ready.append(oid)
            st.total = idx
        except BaseException as e:  # noqa: BLE001 — delivered as item
            st.error = TaskError.from_exception(e)
        st.produced = idx
        st.done = True
        self._streams[spec.task_id.hex()] = st
        return [ObjectRefGenerator(spec.task_id,
                                   spec.return_object_ids()[0], self)]

    def stream_ack(self, task_id, consumed, worker_addr) -> None:
        pass  # eager local streams have no executor to un-block

    def _stream_close(self, task_id) -> None:
        self._streams.pop(task_id.hex(), None)

    def _stream_put_error(self, oid, err) -> None:
        with self._lock:
            self._store[oid] = err

    def create_actor(self, spec: TaskSpec) -> None:
        # Name conflicts must fail BEFORE running the user's __init__ —
        # otherwise the loser leaks a live duplicate instance.
        if spec.actor_name and (spec.namespace,
                                spec.actor_name) in self._named:
            raise ValueError(f"Actor name {spec.actor_name!r} already taken")
        cls = self._load_func(spec)
        try:
            pos, kwargs = self._resolve_args(spec)
            instance = self._run_in_task_context(spec, cls, *pos, **kwargs)
        except BaseException as e:  # noqa: BLE001
            slot = _ActorSlot(None, getattr(cls, "__name__", "?"))
            slot.dead = True
            slot.creation_error = TaskError.from_exception(e)
            self._actors[spec.actor_id] = slot
            return
        slot = _ActorSlot(instance, type(instance).__name__)
        self._actors[spec.actor_id] = slot
        if spec.actor_name:
            key = (spec.namespace, spec.actor_name)
            slot.registered_name = key
            from .api import ActorHandle

            handle = ActorHandle(
                spec.actor_id, slot.class_name,
                [n for n in dir(instance)
                 if not n.startswith("_") and callable(getattr(instance, n))],
                spec.namespace, spec.max_concurrency)
            self._named[key] = handle

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        slot = self._actors.get(spec.actor_id)
        if slot is None or slot.dead:
            err = slot.creation_error if slot else None
            if err is None:
                err = ActorDiedError(spec.actor_id.hex())
            return self._store_error(spec, err)
        try:
            with slot.lock:
                method = getattr(slot.instance, spec.method_name)
                pos, kwargs = self._resolve_args(spec)
                result = self._run_in_task_context(spec, method, *pos, **kwargs)
            return self._store_returns(spec, result)
        except BaseException as e:  # noqa: BLE001
            return self._store_error(spec, ActorError.from_exception(e))

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.for_put(self.current_task_id(), self.next_put_index())
        with self._lock:
            self._store[oid] = value
        return ObjectRef(oid, in_band=True)

    def get(self, refs: List[ObjectRef],
            timeout: Optional[float]) -> List[Any]:
        out = []
        for r in refs:
            v = self._store.get(r.id, _MISSING)
            if v is _MISSING:
                raise KeyError(f"Unknown object {r}")
            if isinstance(v, TaskError):
                raise v
            out.append(v)
        return out

    def wait(self, refs, num_returns, timeout, fetch_local):
        # Local mode is synchronous: everything submitted is already done.
        del timeout, fetch_local
        return refs[:num_returns], refs[num_returns:]

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        slot = self._actors.get(actor_id)
        if slot is not None:
            slot.dead = True
            slot.instance = None
            if slot.registered_name is not None:
                self._named.pop(slot.registered_name, None)
                slot.registered_name = None

    def get_named_actor(self, name: str, namespace: str = ""):
        h = self._named.get((namespace, name))
        if h is None:
            raise ValueError(f"No actor named {name!r} in namespace "
                             f"{namespace!r}")
        return h

    def cancel(self, ref: ObjectRef, force: bool) -> None:
        pass  # local tasks already completed on submission

    def cluster_resources(self) -> Dict[str, float]:
        from .resources import node_resources

        return node_resources().amounts

    def available_resources(self) -> Dict[str, float]:
        return self.cluster_resources()

    def shutdown(self) -> None:
        self._store.clear()
        self._actors.clear()
        self._named.clear()


class _Missing:
    __slots__ = ()


_MISSING = _Missing()
