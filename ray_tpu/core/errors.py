"""User-visible exception hierarchy.

Role-equivalent to the reference's exception set (ref:
python/ray/exceptions.py): errors raised inside remote tasks/actors are
captured, serialized, and re-raised at the ``get()`` site wrapped in a type
that inherits BOTH from TaskError and the user's original exception class,
so ``except ValueError`` still works across the process boundary.
"""

from __future__ import annotations

import traceback

__all__ = [
    "RayTpuError", "TaskError", "ActorError", "ActorDiedError",
    "WorkerCrashedError", "ObjectLostError", "OwnerDiedError",
    "GetTimeoutError", "NodeDiedError", "RuntimeEnvSetupError",
    "OutOfMemoryError", "PlacementGroupUnschedulableError",
    "TaskCancelledError",
]


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception; re-raised at the get() site."""

    def __init__(self, cause_repr: str, traceback_str: str = "",
                 cause: BaseException | None = None):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.cause = cause
        Exception.__init__(self, cause_repr)

    @classmethod
    def from_exception(cls, exc: BaseException) -> "TaskError":
        if isinstance(exc, TaskError):
            return exc
        tb = "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__))
        return make_task_error(repr(exc), tb, exc, cls)

    def __str__(self):
        if not self.traceback_str:
            return self.cause_repr
        return f"{self.cause_repr}\n\nRemote traceback:\n{self.traceback_str}"

    def __reduce__(self):
        import cloudpickle

        cause = self.cause
        if cause is not None:
            try:
                cloudpickle.dumps(cause)
            except Exception:
                cause = None
        kind = ActorError if isinstance(self, ActorError) else TaskError
        return (make_task_error,
                (self.cause_repr, self.traceback_str, cause, kind))


def make_task_error(cause_repr: str, tb: str,
                    cause: BaseException | None,
                    kind: type = TaskError) -> TaskError:
    """Build a TaskError that also subclasses the original exception type,
    mirroring the reference's RayTaskError.as_instanceof_cause (ref:
    python/ray/exceptions.py)."""
    if cause is not None and not isinstance(cause, TaskError):
        base = type(cause)
        if issubclass(base, BaseException) and base not in (Exception,):
            try:
                dual = type(f"{kind.__name__}({base.__name__})",
                            (kind, base), {})
                return dual(cause_repr, tb, cause)
            except TypeError:
                pass
    return kind(cause_repr, tb, cause)


class ActorError(TaskError):
    """An actor task failed or the actor process died."""


class ActorDiedError(ActorError):
    def __init__(self, actor_id_hex: str = "", reason: str = "actor process died"):
        super().__init__(f"ActorDied({actor_id_hex}): {reason}", "")
        self.actor_id_hex = actor_id_hex
        self.reason = reason

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason))


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class ObjectLostError(RayTpuError):
    """An object's value was lost from every node and could not be
    reconstructed from lineage."""

    def __init__(self, object_id_hex: str):
        super().__init__(f"Object {object_id_hex} was lost and is not "
                         f"reconstructable from lineage.")
        self.object_id_hex = object_id_hex

    def __reduce__(self):
        return (type(self), (self.object_id_hex,))


class OwnerDiedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    """get() exceeded its timeout."""


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Task was killed by the memory monitor under node memory pressure."""


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    pass
