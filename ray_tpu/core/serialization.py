"""Object serialization with zero-copy buffer extraction.

Role-equivalent to the reference's serialization glue (ref:
python/ray/_private/serialization.py): cloudpickle for code and arbitrary
Python values, pickle protocol 5 out-of-band buffers so large numpy/JAX
arrays are written into the shared-memory object plane without an extra
copy.  JAX arrays are converted to host numpy on serialize (device transfer
is explicit at the framework layer; objects in the store are host data).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Tuple

# cloudpickle is imported on FIRST USE, not at module import: this
# module sits on every process's import path (worker_main pulls it at
# spawn), and prestarted pool workers must be cheap to fork — most
# never serialize anything until their first task arrives.
_cloudpickle = None


def _cp():
    global _cloudpickle
    if _cloudpickle is None:
        import cloudpickle

        _cloudpickle = cloudpickle
    return _cloudpickle

# Header layout of a stored object:
#   u32 num_buffers | u64 pickled_len | pickled bytes |
#   (u64 buf_len | buf bytes) * num_buffers
_U32 = 4
_U64 = 8


def _to_host(value: Any) -> Any:
    """Convert device arrays to host numpy before pickling (deep conversion
    is handled by cloudpickle calling __reduce__; jax.Array reduces via
    numpy conversion already, but doing it eagerly avoids importing jax in
    the deserializing process)."""
    t = type(value)
    mod = t.__module__
    if mod.startswith("jaxlib") or mod.startswith("jax"):
        import numpy as np

        try:
            return np.asarray(value)
        except Exception:
            return value
    return value


def serialize(value: Any) -> Tuple[bytes, List[memoryview]]:
    """Return (metadata_bytes, out_of_band_buffers)."""
    buffers: List[pickle.PickleBuffer] = []
    value = _to_host(value)
    payload = _cp().dumps(value, protocol=5,
                          buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    return payload, views


def pack(value: Any) -> bytes:
    """Serialize into a single contiguous byte string (header + payload +
    buffers) suitable for writing into one shared-memory segment."""
    payload, views = serialize(value)
    total = _U32 + _U64 + len(payload) + sum(_U64 + len(v) for v in views)
    out = bytearray(total)
    pos = 0
    out[pos:pos + _U32] = len(views).to_bytes(_U32, "little"); pos += _U32
    out[pos:pos + _U64] = len(payload).to_bytes(_U64, "little"); pos += _U64
    out[pos:pos + len(payload)] = payload; pos += len(payload)
    for v in views:
        n = len(v)
        out[pos:pos + _U64] = n.to_bytes(_U64, "little"); pos += _U64
        out[pos:pos + n] = v; pos += n
    return bytes(out)


def pack_into(value: Any, buf: memoryview) -> int:
    """Like pack() but writes directly into a preallocated buffer (the
    shared-memory segment); returns bytes written."""
    data = pack(value)
    buf[: len(data)] = data
    return len(data)


def packed_size(payload: bytes, views: List[memoryview]) -> int:
    return _U32 + _U64 + len(payload) + sum(_U64 + len(v) for v in views)


def unpack(data) -> Any:
    """Inverse of pack(); accepts bytes or memoryview, zero-copy for the
    out-of-band buffers when given a memoryview over shared memory."""
    view = memoryview(data)
    pos = 0
    nbuf = int.from_bytes(view[pos:pos + _U32], "little"); pos += _U32
    plen = int.from_bytes(view[pos:pos + _U64], "little"); pos += _U64
    payload = view[pos:pos + plen]; pos += plen
    buffers = []
    for _ in range(nbuf):
        blen = int.from_bytes(view[pos:pos + _U64], "little"); pos += _U64
        buffers.append(view[pos:pos + blen]); pos += blen
    return pickle.loads(payload, buffers=buffers)


_by_value_registered = set()


def ensure_code_portable(obj: Any) -> None:
    """Make ``obj``'s defining module pickle BY VALUE when worker
    processes can't import it (driver scripts, test modules).  Installed
    site/dist packages and this framework stay by-reference — the
    equivalent of the reference shipping user code via the function
    table + working_dir runtime env rather than expecting importability
    (ref: python/ray/_private/function_manager.py)."""
    import sys

    mod_name = getattr(obj, "__module__", None)
    if (not mod_name or mod_name == "__main__"
            or mod_name in _by_value_registered
            or mod_name.split(".")[0] in ("ray_tpu", "builtins")
            or mod_name.split(".")[0] in sys.stdlib_module_names):
        return
    mod = sys.modules.get(mod_name)
    if mod is None:
        return
    file = getattr(mod, "__file__", "") or ""
    if "site-packages" in file or "dist-packages" in file or not file:
        return
    try:
        _cp().register_pickle_by_value(mod)
        _by_value_registered.add(mod_name)
    except Exception:
        pass


def dumps_code(obj: Any) -> bytes:
    """cloudpickle for code objects shipped to workers."""
    ensure_code_portable(obj)
    return _cp().dumps(obj, protocol=5)


def dumps_message(msg: Any) -> bytes:
    """Control-plane message serialization (small, no out-of-band)."""
    return _cp().dumps(msg, protocol=5)


def loads_message(data: bytes) -> Any:
    return pickle.loads(data)
