"""The node agent — per-node scheduler, worker pool, and object plane.

Role-equivalent to the reference's raylet (ref: src/ray/raylet/
node_manager.h:117 NodeManager, worker_pool.h:216 WorkerPool,
scheduling/cluster_task_manager.h + local_task_manager.h).  One agent per
host: grants worker leases against a resource ledger (hybrid
local-first/spillback policy), spawns and supervises worker processes,
owns the shared-memory store directory, serves node-to-node object
transfer, and holds placement-group bundle reservations (two-phase
prepare/commit, ref: gcs_placement_group_scheduler.h).

TPU note: the agent also owns the host's chip ledger — a lease that
demands ``TPU: k`` is granted k specific chip ids which the worker maps to
``TPU_VISIBLE_CHIPS`` before initializing jax, the TPU analogue of the
reference's CUDA_VISIBLE_DEVICES isolation
(ref: python/ray/_private/accelerators/tpu.py).
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import json
import logging
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from .config import RuntimeConfig
from .ids import ActorID, NodeID, ObjectID, PlacementGroupID, WorkerID
from .object_store import SharedObjectStore, StoreDirectory
from .resources import ResourceSet, node_resources
from .rpc import (RemoteCallError, RpcClient, RpcError, RpcServer,
                  spawn_task)

logger = logging.getLogger("ray_tpu.node_agent")


def pool_plan(*, target: int, idle: int, starting: int, leased: int,
              pending_spawns: int, burst: int, max_workers: int,
              active: int, draining: bool = False) -> int:
    """How many prestart workers to spawn THIS refill tick (pure —
    unit-tested without an agent).

    ``idle``/``starting``/``leased`` count non-actor workers of the env
    hash being refilled: a leased task worker returns to the pool, so
    it still satisfies the target, while an adopted actor worker never
    does.  ``pending_spawns`` vs ``burst`` is the spawn-storm
    hysteresis — at most ``burst`` forked-but-unregistered processes
    exist at once, so a refill after a mass adoption trickles the herd
    instead of forking it in one stampede.  A draining node never
    refills (its pool is being killed, not warmed)."""
    if draining or target <= 0:
        return 0
    deficit = target - idle - starting - leased
    if deficit <= 0:
        return 0
    budget = burst - pending_spawns
    room = max_workers - active
    return max(0, min(deficit, budget, room))


def warm_env_targets(now: float, default_target: int,
                     env_last_used: Dict[str, float],
                     ttl_s: float) -> Dict[str, int]:
    """Which runtime-env hashes the prestart pool keeps warm: the
    default env always, plus any hash adopted within ``ttl_s`` (each at
    the full target — the reference pops workers by runtime-env hash,
    worker_pool.h:216, so a hot non-default env deserves its own warm
    set)."""
    out = {"": default_target}
    for env_hash, ts in env_last_used.items():
        if env_hash and now - ts <= ttl_s:
            out[env_hash] = default_target
    return out


@dataclass
class WorkerEntry:
    worker_id: WorkerID
    addr: str
    pid: int
    proc: Optional[subprocess.Popen] = None
    state: str = "idle"  # starting | idle | leased | actor | dead
    actor_id: Optional[ActorID] = None
    lease_id: Optional[int] = None
    # Runtime-env identity: a worker only serves leases with a matching
    # env hash (ref: worker_pool.h:216 PopWorker runtime-env keying).
    env_hash: str = ""
    # Log plane: this worker's stdout/stderr file and the job its
    # current/last lease belongs to (log lines are attributed to it —
    # ref: _private/log_monitor.py job tagging).
    log_path: str = ""
    job_id: Optional[str] = None
    # True once this worker has served a lease and returned to the
    # idle pool: a waiter handed a recycled worker paid NO fork, so
    # the pool's cold-spawn (fork-latency) accounting must not count
    # it (doctor's exhaustion check keys off that counter).
    recycled: bool = False


@dataclass
class Lease:
    lease_id: int
    resources: ResourceSet
    worker: WorkerEntry
    chip_ids: List[int]
    pg_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    blocked: bool = False
    # Connection tag of the OWNER process holding this lease (task/pool
    # leases only; actor leases are owned by the actor worker itself
    # and released on its exit).  Lets the agent reclaim leases whose
    # owner died without returning them — e.g. an actor killed while
    # caching a lease for reuse — instead of stranding the leased
    # worker and its resources forever.
    owner_tag: str = ""
    granted_ts: float = 0.0
    # Internal job hex of the submitting driver — resolves to the
    # multi-tenant submitted-job id through the controller's
    # heartbeat-distributed job view (quota enforcement + per-job
    # attribution in the lease ledger).
    job_id: str = ""


@dataclass
class _PendingLease:
    payload: Dict[str, Any]
    future: asyncio.Future
    enqueue_time: float = field(default_factory=time.time)


@dataclass
class _Bundle:
    pg_id: PlacementGroupID
    bundle_index: int
    resources: ResourceSet
    committed: bool = False
    in_use: ResourceSet = field(default_factory=ResourceSet)


class NodeAgent:
    def __init__(self, config: RuntimeConfig, session: str,
                 controller_addr: str, *,
                 num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 custom_resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 is_head: bool = False):
        self.config = config
        self.session = session
        self.controller_addr = controller_addr
        self.node_id = NodeID.from_random()
        self.is_head = is_head
        self.labels = labels or {}
        self.total = node_resources(
            num_cpus=num_cpus, num_tpus=num_tpus, extra=custom_resources,
            tpu_override_chips=config.tpu_chips_per_host)
        self.available = self.total.copy()
        n_chips = int(self.total.get("TPU"))
        self.free_chips: List[int] = list(range(n_chips))
        self.server = RpcServer()
        from .object_store import PoolObjectStore, create_store

        self.store = create_store(session, config)
        # Workers must use the SAME backend this agent resolved — a
        # silent per-process fallback would split the node across two
        # object planes.
        self._store_backend = ("pool" if isinstance(self.store,
                                                    PoolObjectStore)
                               else "segments")
        spill_dir = None
        if config.object_spill_enabled:
            spill_dir = os.path.join(
                config.session_dir_root, session, "spill",
                self.node_id.hex()[:8])
        self.directory = StoreDirectory(
            self.store, config.object_store_memory_bytes,
            spill_dir=spill_dir)
        self.workers: Dict[WorkerID, WorkerEntry] = {}
        self.leases: Dict[int, Lease] = {}
        self.bundles: Dict[Tuple[PlacementGroupID, int], _Bundle] = {}
        self.pending: List[_PendingLease] = []
        self._lease_counter = itertools.count(1)
        self._starting_workers = 0
        self._idle_q: List[WorkerEntry] = []
        self._worker_ready = asyncio.Event()
        self._pull_inflight: Dict[ObjectID, asyncio.Future] = {}
        # Fast releases that arrived before their registration (cross-
        # channel reorder); the late register must be dropped.
        self._early_released: set = set()
        # Coalesced location updates -> controller (ordered add/remove
        # pairs); flushed after a short window so a put/release burst
        # costs one bulk notify, not a call round trip per object.
        self._loc_buf: List = []
        self._loc_flush_scheduled = False
        self._loc_send_inflight = False
        self._ctl: Optional[RpcClient] = None
        self._peer_agents: Dict[str, RpcClient] = {}
        self._resource_view: Dict[Any, Dict] = {}
        # Drain lifecycle (preemption notice / `rt drain`): a draining
        # agent refuses new lease grants, redirects its queued lease
        # requests to live peers, and advertises the drain deadline in
        # its heartbeat so the controller/autoscaler can migrate work
        # and start a replacement BEFORE the node dies.
        self._draining = False
        self._drain_reason = ""
        self._drain_deadline = 0.0
        self._drain_replace = True
        # Lease-ledger view state (`rt list leases` / `rt doctor`):
        # owner-reported pipeline depth per lease, when an owner tag's
        # connection was first seen lost, and per-lease disconnect
        # anchors derived from it.
        self._owner_lease_depths: Dict[int, tuple] = {}
        self._owner_conn_lost_ts: Dict[str, float] = {}
        self._owner_disc_since: Dict[int, float] = {}
        # Multi-tenant quota view from heartbeat replies:
        # {internal_job_hex: {job, priority, quota, used}} — the
        # lease-grant path refuses (queues) grants that would run a
        # job over quota.  Last-reported local usage lets the grant
        # check overlay its own since-last-heartbeat deltas.
        self._job_view: Dict[str, Dict] = {}
        self._job_usage_reported: Dict[str, Dict[str, float]] = {}
        self._shutdown = asyncio.Event()
        self._spawned_procs: List[subprocess.Popen] = []
        # Warm-worker prestart pool (ref: worker_pool.h:216 PopWorker /
        # PrestartWorkers): idle workers pre-spawned per runtime-env
        # hash so actor/task creation ADOPTS a live process instead of
        # paying a full interpreter spawn.  Counters feed `rt
        # telemetry`, `rt doctor` (pool exhaustion), and the scale
        # benches' adoption-vs-cold-spawn report.
        self._pool_adoptions = 0
        self._pool_cold_spawns = 0
        self._cold_spawn_ts: List[float] = []  # ring for the 60s window
        self._spawned_total = 0
        self._env_specs: Dict[str, Dict] = {}      # hash -> runtime_env
        self._env_last_used: Dict[str, float] = {}
        self._refill_wakeup = asyncio.Event()
        # Worker startup-phase breakdown (spawn/import/connect stamped
        # into the worker hello; adopt measured grant-side).
        from ..util.metrics import Histogram

        self._startup_hist = Histogram(
            "rt_worker_startup_seconds",
            "Worker startup time by phase (spawn=fork->interpreter, "
            "import=module imports, connect=runtime connect+hello, "
            "adopt=lease-grant wait for a worker).",
            tag_keys=("phase",))
        # Batched actor-started relay: workers report their actor hello
        # here; the agent coalesces a creation fan-out into bulk
        # controller RPCs on a short window (one persistent connection,
        # a handful of frames — not one fresh dial per actor).
        self._actor_started_buf: List[Tuple[Dict, asyncio.Future]] = []
        self._actor_started_scheduled = False
        for name in [
            "request_lease", "return_lease", "lease_status",
            "cancel_lease_request", "list_leases", "report_lease_pool",
            "register_worker", "worker_heartbeat",
            "report_task_events", "report_metrics", "report_spans",
            "report_collective_entries",
            "jax_profile_workers",
            "task_blocked", "task_unblocked", "report_backlog",
            "register_object", "pull_object", "fetch_raw", "fetch_chunk",
            "delete_object", "owner_release_local", "make_room",
            "object_exists", "objects_exist", "store_stats",
            "prepare_bundle", "commit_bundle", "return_bundle",
            "restart_actor", "kill_worker", "report_actor_failure",
            "report_actor_started", "pool_stats",
            "preempt_pg_leases",
            "drain", "shutdown", "ping", "node_info", "list_workers",
            "list_worker_logs", "read_worker_log", "profile_worker",
            "stack_worker",
        ]:
            self.server.register(name, getattr(self, name))
        # Reclaim leases whose owner process died without returning
        # them (found via the new tracing tests: a killed actor that
        # had cached a task lease for reuse strands the leased worker
        # and its CPUs forever, starving every later task).
        self.server.on_connection_lost(self._on_owner_conn_lost)

    # -------------------------------------------------------------- startup
    async def start(self, port: int = 0) -> int:
        # Debug hook: `kill -USR2 <agent pid>` logs every live asyncio
        # task with its await stack (coroutine-level triage the
        # faulthandler thread dump can't see).
        def _dump_tasks(*_a):
            logger.error(
                "SCHEDSTATE pending=%d workers=%d idle_q=%d "
                "starting=%d spawns=%d available=%s total=%s "
                "leases=%s free_chips=%s by_env=%s acq=%s",
                len(self.pending), len(self.workers),
                len(self._idle_q), self._starting_workers,
                len(getattr(self, "_pending_spawns", {})),
                dict(self.available.amounts),
                dict(self.total.amounts),
                {lid: dict(l.resources.amounts)
                 for lid, l in self.leases.items()},
                self.free_chips,
                dict(getattr(self, "_starting_by_env", {})),
                dict(getattr(self, "_acquirers_by_env", {})))
            for t in asyncio.all_tasks():
                # Walk the cr_await chain so nested handler coroutines
                # show their INNERMOST suspension point, not just the
                # outer _dispatch frame.
                lines = []
                coro = t.get_coro()
                seen = 0
                while coro is not None and seen < 32:
                    seen += 1
                    frame = getattr(coro, "cr_frame", None) or \
                        getattr(coro, "gi_frame", None)
                    if frame is not None:
                        code = frame.f_code
                        lines.append(f"  {code.co_filename}:"
                                     f"{frame.f_lineno} "
                                     f"{code.co_name}")
                    nxt = getattr(coro, "cr_await", None) or \
                        getattr(coro, "gi_yieldfrom", None)
                    if nxt is coro:
                        break
                    coro = nxt
                logger.error("TASKDUMP %r\n%s", t,
                             "\n".join(lines) or "  <no frames>")

        try:
            asyncio.get_event_loop().add_signal_handler(
                signal.SIGUSR2, _dump_tasks)
        except (NotImplementedError, RuntimeError):
            pass
        # Preemption notice: GCP delivers SIGTERM seconds-to-minutes
        # before a spot VM dies.  Enter DRAINING instead of dying so
        # the grace window is spent migrating work (checkpoint-on-
        # notice, queued-lease redirect) rather than lost.  A REPEATED
        # SIGTERM forces immediate shutdown (operator escape hatch) —
        # but only once a SIGTERM already armed the deadline: the
        # first SIGTERM on a node mid `rt drain` is the real cloud
        # notice, and discarding its grace would kill gangs mid
        # checkpoint-on-notice.
        def _on_sigterm():
            if getattr(self, "_sigterm_drained", False):
                spawn_task(self.shutdown())
            elif self._draining:
                self._sigterm_drained = True
                now = time.time()
                grace = self.config.preemption_grace_s
                if self._drain_deadline > 0:
                    self._drain_deadline = min(self._drain_deadline,
                                               now + grace)
                else:
                    self._drain_deadline = now + grace
                asyncio.get_event_loop().call_later(
                    max(self._drain_deadline - now, 0.0),
                    lambda: spawn_task(self.shutdown()))
            elif not self.leases and not self.pending \
                    and not self.bundles:
                # Nothing to migrate: spending the grace window on an
                # idle node only slows down `rt stop` / graceful
                # teardown paths that relied on SIGTERM exiting.
                self._sigterm_drained = True
                spawn_task(self.shutdown())
            else:
                self._sigterm_drained = True
                spawn_task(self._begin_drain(
                    reason="preemption notice (SIGTERM)",
                    grace_s=self.config.preemption_grace_s,
                    shutdown_at_deadline=True))

        try:
            asyncio.get_event_loop().add_signal_handler(
                signal.SIGTERM, _on_sigterm)
        except (NotImplementedError, RuntimeError):
            pass
        await self.server.start(port)
        # Evictions from ANY shed site (read-window expiry, restore
        # pressure, register) must drop their controller locations, or
        # recovery probes poll dead copies until timeout.
        self._loop = asyncio.get_event_loop()

        def _on_evict(oids):
            # Through the ORDERED update queue (thread-safe hop onto
            # the loop): an immediate direct remove could overtake a
            # still-buffered add for the same oid and leave a ghost
            # location — every location mutation from this agent rides
            # one serialized, acked stream.
            def _q():
                for oid in oids:
                    self._queue_loc_update("remove", oid)

            self._loop.call_soon_threadsafe(_q)

        self.directory.on_evict = _on_evict
        self._ctl = RpcClient(self.controller_addr,
                              tag=f"agent-{self.node_id.hex()[:8]}",
                              connect_timeout=5.0)
        await self._ctl.connect()
        await self._ctl.call("register_node", {
            "node_id": self.node_id, "agent_addr": self.server.address,
            "resources": dict(self.total.amounts), "labels": self.labels,
            "is_head": self.is_head})
        # Event-loop lag ring: a starved agent loop (fork herds, big
        # frame decodes) shows up as rt_loop_lag_seconds in telemetry
        # and as an rt doctor event-loop-stall finding.
        from ..util.hotpath import LoopLagSampler

        self._loop_lag = LoopLagSampler(self._loop)
        self._loop_lag.start()
        spawn_task(self._heartbeat_loop())
        spawn_task(self._reap_loop())
        if self.config.log_to_driver:
            spawn_task(self._log_monitor_loop())
        if self.config.memory_monitor_refresh_ms > 0:
            spawn_task(self._memory_monitor_loop())
        for _ in range(self.config.worker_pool_min_workers):
            self._spawn_worker()
        spawn_task(self._prestart_refill_loop())
        return self.server.port

    async def _heartbeat_loop(self) -> None:
        period = self.config.raylet_heartbeat_period_ms / 1000.0
        first_miss = None
        last_metrics = 0.0
        self._last_busy = time.time()
        while not self._shutdown.is_set():
            try:
                now = time.time()
                if self.leases or self.bundles:
                    self._last_busy = now
                # Demand = queued lease requests + owner-reported
                # backlogs (lease requests are rate-limited per owner,
                # so queued tasks beyond the in-flight requests arrive
                # via report_backlog; ref: ReportWorkerBacklog in
                # normal_task_submitter.h).
                demands = self._demand_vector()
                # Snapshot ONCE and remember exactly what was sent:
                # recomputing after the RPC await would fold leases
                # granted mid-await into the "already reported" side
                # of the quota overlay and hide them from the check.
                job_usage = self._job_usage_local()
                if self.pending:
                    # Self-healing dispatch tick: a request requeued
                    # after a failed worker acquire has no event left
                    # to kick it; retry on the heartbeat cadence (ref:
                    # the raylet re-running ScheduleAndDispatchTasks
                    # periodically, node_manager.cc).
                    self._kick_scheduler()
                r = await self._ctl.call("heartbeat", {
                    "node_id": self.node_id,
                    "available": {k: max(v, 0.0) for k, v in
                                  self.available.amounts.items()},
                    "total": dict(self.total.amounts),
                    # Autoscaler inputs (ref: ray_syncer.proto:31-47
                    # idle_duration_ms + LoadMetrics demand vector).
                    "idle_s": now - self._last_busy,
                    "pending_demands": demands,
                    # Drain plane: the controller mirrors these into
                    # its node table (`rt drain` state, doctor's
                    # stale-drain check, autoscaler replacement).
                    # The deadline crosses hosts as REMAINING seconds
                    # — agent wall clocks can sit minutes off the
                    # controller's, and the stale-drain check compares
                    # against the controller clock (same receipt-clock
                    # discipline as flight-dump ages).
                    "draining": self._draining,
                    "drain_remaining_s": self._drain_remaining(),
                    "drain_reason": self._drain_reason,
                    "drain_replace": self._drain_replace,
                    # Multi-tenant accounting: plain-lease usage per
                    # internal job (PG-bound leases excluded — their
                    # bundles are counted controller-side).
                    "job_usage": job_usage,
                    # Prestart-pool occupancy for `rt status` / the
                    # dashboard node table.  Prestarted IDLE workers
                    # deliberately do NOT touch _last_busy above:
                    # a warm pool must never pin a node past its
                    # idle timeout (the autoscaler's if_idle reap
                    # and scale-down read idle_s).
                    "worker_pool": {
                        "idle": self._pool_counts("")[0],
                        "target": self._prestart_target(),
                        "adoptions": self._pool_adoptions,
                        "cold_spawns": self._pool_cold_spawns}})
                self._job_usage_reported = job_usage
                self._job_view = r.get("jobs") or {}
                now = time.time()
                if now - last_metrics >= \
                        self.config.metrics_report_period_s:
                    last_metrics = now
                    await self._ctl.call("report_metrics", {
                        "source": f"node-{self.node_id.hex()[:8]}",
                        "snapshot": self._node_metrics_snapshot()})
                if r.get("reregister"):
                    # Fresh (possibly restarted) controller: rebuild our
                    # node row AND our object locations (the location
                    # directory is not persisted; ref: NotifyGCSRestart
                    # node_manager.proto:387 resend path).
                    await self._ctl.call("register_node", {
                        "node_id": self.node_id,
                        "agent_addr": self.server.address,
                        "resources": dict(self.total.amounts),
                        "labels": self.labels, "is_head": self.is_head})
                    objs = [(oid, ent.size) for oid, ent in
                            [(o, self.directory.lookup(o))
                             for o in self.directory.all_ids()]
                            if ent is not None]
                    if objs:
                        await self._ctl.call("publish_locations", {
                            "node_id": self.node_id, "objects": objs})
                first_miss = None
            except RpcError:
                now = time.time()
                if first_miss is None:
                    first_miss = now
                # Tolerate a restart window: RpcClient re-dials on the
                # next call, so a controller that comes back on the same
                # address within the grace resumes us transparently.
                if now - first_miss > \
                        self.config.controller_reconnect_grace_s:
                    logger.warning("controller unreachable for %.0fs; "
                                   "shutting down",
                                   now - first_miss)
                    await self.shutdown()
                    return
            await asyncio.sleep(period)

    @staticmethod
    def _memory_usage_fraction() -> float:
        """Host memory pressure from /proc/meminfo (ref:
        common/memory_monitor.h GetMemoryBytes — cgroup-aware there;
        host-level here, which matches one-agent-per-TPU-host)."""
        total = avail = None
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = int(line.split()[1])
                    if total is not None and avail is not None:
                        break
        except OSError:
            return 0.0
        if not total or avail is None:
            return 0.0  # no MemAvailable (old kernel): monitor inert
        return 1.0 - avail / total

    def _pick_oom_victim(self) -> Optional["Lease"]:
        """Retriable-task-first, newest-first (ref:
        worker_killing_policy.h RetriableFIFOWorkerKillingPolicy):
        normal tasks retry transparently; actors lose state, so they go
        last — and only when they are restartable is that survivable."""
        task_leases = [ls for ls in self.leases.values()
                       if ls.worker.state == "leased"]
        if task_leases:
            return max(task_leases, key=lambda ls: ls.lease_id)
        actor_leases = [ls for ls in self.leases.values()
                        if ls.worker.state == "actor"]
        if actor_leases:
            return max(actor_leases, key=lambda ls: ls.lease_id)
        return None

    async def _memory_monitor_loop(self) -> None:
        """Kill workers under host memory pressure instead of letting
        the OS OOM killer take the agent (ref: memory_monitor.h +
        worker_killing_policy.h)."""
        period = self.config.memory_monitor_refresh_ms / 1000.0
        threshold = self.config.memory_usage_threshold
        while not self._shutdown.is_set():
            await asyncio.sleep(period)
            usage = self._memory_usage_fraction()
            if usage <= threshold:
                continue
            victim = self._pick_oom_victim()
            if victim is None:
                continue
            w = victim.worker
            logger.warning(
                "memory pressure %.1f%% > %.1f%%: killing worker %s "
                "(lease %d) to reclaim memory", usage * 100,
                threshold * 100, w.pid, victim.lease_id)
            try:
                if w.proc is not None:
                    w.proc.kill()
                else:
                    os.kill(w.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            # The reap loop notices the death, releases the lease, and
            # the owner's retry machinery resubmits retriable work.

    async def _reap_loop(self) -> None:
        """Detect worker process exits (ref: worker_pool.cc monitoring)."""
        while not self._shutdown.is_set():
            await asyncio.sleep(0.1)
            for w in list(self.workers.values()):
                if w.proc is not None and w.proc.poll() is not None \
                        and w.state != "dead":
                    await self._on_worker_exit(w)
            # Workers that died before registering.
            pending = getattr(self, "_pending_spawns", {})
            for pid, (proc, env_hash) in list(pending.items()):
                if proc.poll() is not None:
                    pending.pop(pid, None)
                    self._starting_done(env_hash)
                    self._worker_ready.set()
                    logger.warning("worker pid %s died before registering "
                                   "(code %s)", pid, proc.returncode)

    async def _on_worker_exit(self, w: WorkerEntry) -> None:
        prev_state = w.state
        w.state = "dead"
        self.workers.pop(w.worker_id, None)
        if w in self._idle_q:
            self._idle_q.remove(w)
        # A death frees a pool slot: waiters in _acquire_worker must
        # re-evaluate their spawn budget or they sleep out their full
        # timeout while the pool sits empty.
        self._worker_ready.set()
        self._kick_refill()
        if w.lease_id is not None and w.lease_id in self.leases:
            self._release_lease(self.leases[w.lease_id], worker_back=False)
        if prev_state == "actor" and w.actor_id is not None:
            code = w.proc.returncode if w.proc else None
            try:
                await self._ctl.call("actor_died", {
                    "actor_id": w.actor_id,
                    "reason": f"worker exited with code {code}"})
            except RpcError:
                pass
        await self._forward_flight_dump(w)
        logger.info("worker %s exited (state=%s)", w.pid, prev_state)

    async def _forward_flight_dump(self, w: WorkerEntry) -> None:
        """If the dead worker left a flight-recorder dump, ship it to
        the controller so postmortems work cluster-wide (the file stays
        on disk for offline triage)."""
        path = os.path.join(
            self.config.session_dir_root, self.session, "flight",
            f"worker-{self.node_id.hex()[:8]}-{w.pid}.json")
        try:
            if not os.path.exists(path):
                return
            with open(path) as f:
                data = json.load(f)
            await self._ctl.call("report_flight_dump", {
                "source": data.get("source") or f"worker-{w.pid}",
                "reason": data.get("reason", ""),
                "ts": data.get("ts"), "path": path,
                "sticky": data.get("sticky") or {},
                "events": (data.get("events") or [])[-200:]})
        except (OSError, ValueError, RpcError):
            pass

    # --------------------------------------------------------- worker pool
    def _spawn_worker(self, runtime_env: Optional[Dict] = None) -> None:
        env = dict(os.environ)
        env.update(self.config.env_overrides())
        if int(self.total.get("TPU")) == 0:
            # CPU-only node: drop the axon TPU-relay trigger so the
            # image's sitecustomize doesn't preload jax into every
            # worker (~2s of a ~2.8s spawn measured) — tasks that
            # import jax still get the CPU backend.
            env.pop("PALLAS_AXON_POOL_IPS", None)
        env_hash = ""
        if runtime_env:
            env_hash = runtime_env.get("hash", "")
            env.update(runtime_env.get("env_vars", {}))
            env["RT_RUNTIME_ENV"] = json.dumps(runtime_env)
        # Control-plane vars LAST: user env_vars must never override the
        # addresses the worker needs to register at all.
        env.update({
            "RT_SESSION_NAME": self.session,
            "RT_CONTROLLER_ADDR": self.controller_addr,
            "RT_AGENT_ADDR": self.server.address,
            "RT_NODE_ID": self.node_id.hex(),
            "RT_OBJECT_STORE_BACKEND": self._store_backend,
            # Startup-phase anchor: the worker stamps its hello with
            # spawn/import/connect durations measured from this fork
            # time (rt_worker_startup_seconds).
            "RT_SPAWN_TS": repr(time.time()),
        })
        self._spawned_total += 1
        log_dir = os.path.join(self.config.session_dir_root, self.session,
                               "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(
            log_dir, f"worker-{self.node_id.hex()[:8]}-"
            f"{self._spawned_total}-{time.time():.0f}.log")
        out = open(log_path, "ab")
        # pip envs: spawn the trampoline, which builds/reuses the venv
        # (file-locked, off this event loop) and execs worker_main
        # under the venv python (ref: _private/runtime_env/pip.py —
        # the worker STARTS inside its environment).
        if runtime_env and runtime_env.get("pip"):
            module = "ray_tpu.runtime_env.pip_bootstrap"
        elif runtime_env and runtime_env.get("uv"):
            module = "ray_tpu.runtime_env.uv_bootstrap"
        else:
            module = "ray_tpu.core.worker_main"
        try:
            proc = subprocess.Popen(
                [sys.executable, "-u", "-m", module],
                env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True)
        finally:
            # The starting/_starting_by_env bookkeeping happens only
            # AFTER a successful fork: a raising Popen (EAGAIN/ENOMEM
            # under exactly the fork storms the pool creates) must
            # not permanently inflate the spawn budgets.
            out.close()
        self._starting_workers += 1
        self._worker_log_paths = getattr(self, "_worker_log_paths", {})
        self._worker_log_paths[proc.pid] = log_path
        self._spawned_procs.append(proc)
        self._pending_spawns = getattr(self, "_pending_spawns", {})
        self._pending_spawns[proc.pid] = (proc, env_hash)
        by_env = getattr(self, "_starting_by_env", None)
        if by_env is None:
            by_env = self._starting_by_env = {}
        by_env[env_hash] = by_env.get(env_hash, 0) + 1

    def _starting_done(self, env_hash: str) -> None:
        self._starting_workers = max(0, self._starting_workers - 1)
        by_env = getattr(self, "_starting_by_env", {})
        if env_hash in by_env:
            by_env[env_hash] = max(0, by_env[env_hash] - 1)

    async def register_worker(self, p):
        pending = getattr(self, "_pending_spawns", {}).pop(
            p["pid"], (None, ""))
        if self._draining:
            # A spawn that raced the drain decision: this worker can
            # never be adopted (grants are refused) — kill it now
            # instead of parking a useless process through the grace.
            self._starting_done(pending[1])
            try:
                if pending[0] is not None:
                    pending[0].kill()
                else:
                    os.kill(p["pid"], signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            return {"ok": False, "draining": True,
                    "node_id": self.node_id}
        w = WorkerEntry(
            worker_id=p["worker_id"], addr=p["addr"], pid=p["pid"],
            proc=pending[0], state="idle", env_hash=pending[1],
            log_path=getattr(self, "_worker_log_paths",
                             {}).get(p["pid"], ""))
        self.workers[w.worker_id] = w
        self._starting_done(w.env_hash)
        self._idle_q.append(w)
        self._worker_ready.set()
        for phase, dt in (p.get("phases") or {}).items():
            try:
                self._startup_hist.observe(float(dt),
                                           tags={"phase": str(phase)})
            except (TypeError, ValueError):
                pass
        self._kick_scheduler()
        return {"ok": True, "node_id": self.node_id}

    async def worker_heartbeat(self, p):
        return {"ok": True}

    async def report_backlog(self, p):
        """Owner-side per-scheduling-key backlog report (notify; ref:
        ReportWorkerBacklog in normal_task_submitter.h) — folded into
        the heartbeat's demand vector with a freshness TTL so demand
        from a dead owner ages out."""
        backlogs = getattr(self, "_owner_backlogs", None)
        if backlogs is None:
            backlogs = self._owner_backlogs = {}
        key = (p.get("owner"), p.get("key"))
        if not p.get("backlog"):
            backlogs.pop(key, None)
        else:
            backlogs[key] = (dict(p["resources"]),
                             int(p["backlog"]), time.time())
        return {"ok": True}

    def _demand_vector(self):
        """This node's current unsatisfied demand: queued lease
        requests + owner-reported backlogs + autoscaler-held
        infeasible demands (the vector the heartbeat advertises and
        `rt list leases` exposes for diagnosis)."""
        demands = [dict(req.payload["resources"])
                   for req in self.pending][:100]
        demands += self._backlog_demands()
        demands += list(getattr(self, "_infeasible", []))[:100]
        return demands

    def _backlog_demands(self, cap: int = 100):
        """Fresh owner backlogs as a demand list for the autoscaler."""
        backlogs = getattr(self, "_owner_backlogs", {})
        now = time.time()
        out = []
        for key, (res, n, ts) in list(backlogs.items()):
            if now - ts > 5.0:
                backlogs.pop(key, None)
                continue
            out.extend([dict(res)] * min(n, 20))
            if len(out) >= cap:
                break
        return out[:cap]

    async def report_task_events(self, p):
        """Relay worker task events to the controller sink (workers have
        no persistent controller connection; the agent does)."""
        try:
            await self._ctl.call("task_events", {"events": p["events"]})
        except RpcError:
            pass
        return {"ok": True}

    async def report_metrics(self, p):
        try:
            await self._ctl.call("report_metrics", p)
        except RpcError:
            pass
        return {"ok": True}

    async def report_spans(self, p):
        """Relay a worker's drained span ring to the controller's span
        sink (workers have no persistent controller connection; this
        is the same relay report_task_events rides)."""
        p.setdefault("node_id", self.node_id.hex())
        try:
            await self._ctl.call("report_spans", p)
        except RpcError:
            pass
        return {"ok": True}

    async def jax_profile_workers(self, p):
        """Fan an on-demand jax.profiler capture out to every live
        worker on this node (ref: the reference dashboard's
        profile_manager; here the capture runs in-process on the
        worker and the artifact path is reported back through the
        controller so `rt profile --jax` can list it cluster-wide)."""
        req = {"duration_s": p.get("duration_s", 3.0),
               "log_dir": p.get("log_dir"), "force": p.get("force")}

        async def _one(w):
            cli = RpcClient(w.addr, tag="jaxprof")
            try:
                r = await cli.call("jax_profile", req)
            except RpcError as e:
                r = {"ok": False, "error": str(e)}
            finally:
                await cli.close()
            return {"pid": w.pid, "worker_id": w.worker_id.hex(), **r}

        results = await asyncio.gather(
            *[_one(w) for w in list(self.workers.values())])
        for r in results:
            if r.get("ok") and r.get("path"):
                try:
                    await self._ctl.call("report_profile", {
                        "source": f"worker-{self.node_id.hex()[:8]}"
                                  f"-{r['pid']}",
                        "kind": "jax", "path": r["path"],
                        "node_id": self.node_id.hex(),
                        "ts": time.time()})
                except RpcError:
                    pass
        return {"ok": True, "node_id": self.node_id.hex(),
                "results": list(results)}

    def _host_cpu_util(self) -> float:
        """Host CPU utilization since the previous sample, from
        /proc/stat deltas (ref: dashboard/modules/reporter/
        reporter_agent.py psutil.cpu_percent; /proc keeps the agent
        dependency-free)."""
        try:
            with open("/proc/stat") as f:
                parts = f.readline().split()[1:]
            vals = [int(x) for x in parts[:8]]
        except (OSError, ValueError):
            return 0.0
        total = sum(vals)
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
        prev = getattr(self, "_prev_cpu_sample", None)
        self._prev_cpu_sample = (total, idle)
        if prev is None or total <= prev[0]:
            return 0.0
        dt = total - prev[0]
        return max(0.0, min(1.0, 1.0 - (idle - prev[1]) / dt))

    def _node_metrics_snapshot(self) -> List[Dict]:
        n_obj, used, cap = self.directory.stats()
        spill = self.directory.spill_stats()
        states: Dict[str, int] = {}
        for w in self.workers.values():
            states[w.state] = states.get(w.state, 0) + 1
        pool_idle, _starting, _leased = self._pool_counts("")
        # The agent's own registry carries rt_worker_startup_seconds
        # (the only registry metric in this process) — ship it with
        # the node snapshot so `rt telemetry` sees the phase
        # histogram without a separate reporting channel.  Loop-lag
        # quantiles and per-method RPC handler stats ride the same
        # snapshot (control-plane introspection, util/hotpath.py).
        from ..util.metrics import registry

        lag = getattr(self, "_loop_lag", None)
        extra = (lag.metric_snaps() if lag is not None else []) \
            + self.server.stats.metric_snaps()
        return list(registry().snapshot()) + extra + [
            {"name": "rt_worker_pool_idle", "kind": "gauge",
             "description": "Prestarted idle workers ready for "
                            "adoption (default runtime env).",
             "series": [{"tags": {}, "value": pool_idle}]},
            {"name": "rt_worker_pool_target", "kind": "gauge",
             "description": "Prestart pool target size.",
             "series": [{"tags": {},
                         "value": self._prestart_target()}]},
            {"name": "rt_worker_adoptions_total", "kind": "counter",
             "description": "Lease grants served by adopting a warm "
                            "pooled worker (cumulative).",
             "series": [{"tags": {}, "value": self._pool_adoptions}]},
            {"name": "rt_worker_cold_spawn_total", "kind": "counter",
             "description": "Lease grants that had to wait for a "
                            "worker process spawn (cumulative).",
             "series": [{"tags": {},
                         "value": self._pool_cold_spawns}]},
        ] + [
            {"name": "rt_node_cpu_util", "kind": "gauge",
             "description": "Host CPU utilization (0-1).",
             "series": [{"tags": {},
                         "value": self._host_cpu_util()}]},
            {"name": "rt_node_mem_util", "kind": "gauge",
             "description": "Host memory utilization (0-1).",
             "series": [{"tags": {},
                         "value": self._memory_usage_fraction()}]},
            {"name": "rt_node_workers", "kind": "gauge",
             "description": "Worker processes by state.",
             "series": [{"tags": {"state": s}, "value": v}
                        for s, v in states.items()]},
            {"name": "rt_node_leases_active", "kind": "gauge",
             "description": "Granted worker leases.",
             "series": [{"tags": {}, "value": len(self.leases)}]},
            {"name": "rt_node_leases_pending", "kind": "gauge",
             "description": "Queued lease requests.",
             "series": [{"tags": {}, "value": len(self.pending)}]},
            {"name": "rt_node_object_store_bytes", "kind": "gauge",
             "description": "Local shared-memory store usage.",
             "series": [{"tags": {"kind": "used"}, "value": used},
                        {"tags": {"kind": "capacity"}, "value": cap}]},
            {"name": "rt_node_objects", "kind": "gauge",
             "description": "Objects in the local store.",
             "series": [{"tags": {}, "value": n_obj}]},
            {"name": "rt_node_resources_available", "kind": "gauge",
             "description": "Schedulable resources available.",
             "series": [{"tags": {"resource": k}, "value": v}
                        for k, v in self.available.amounts.items()]},
            # Object-plane spill counters: these previously died
            # in-process (visible only via the store_stats RPC nobody
            # polls); as metrics they ride the heartbeat into
            # `rt telemetry` / Prometheus.
            {"name": "rt_object_spilled_bytes", "kind": "gauge",
             "description": "Bytes currently spilled to disk by the "
                            "local object store.",
             "series": [{"tags": {},
                         "value": spill["spilled_bytes"]}]},
            {"name": "rt_object_spill_total", "kind": "counter",
             "description": "Objects spilled to disk (cumulative).",
             "series": [{"tags": {}, "value": spill["spill_count"]}]},
            {"name": "rt_object_restore_total", "kind": "counter",
             "description": "Spilled objects restored into shm "
                            "(cumulative).",
             "series": [{"tags": {},
                         "value": spill["restore_count"]}]},
        ]

    def _max_workers(self) -> int:
        cap = self.config.worker_pool_max_workers
        if cap > 0:
            return cap
        return max(int(self.total.get("CPU")) * 4, 16)

    async def _acquire_worker(self, runtime_env: Optional[Dict] = None
                              ) -> Optional[WorkerEntry]:
        # Spawns are bounded by live demand (waiting acquirers), not by the
        # wake-up rate — otherwise every near-miss wake-up forks another
        # interpreter and a 1-core host death-spirals.  Both counters are
        # per runtime-env hash: a worker warming up for env A must not
        # satisfy the spawn budget of a request for env B.
        want = (runtime_env or {}).get("hash", "")
        if want:
            # Remember the env so the prestart pool can keep it warm
            # (and can re-spawn workers INSIDE it after adoptions).
            self._env_specs[want] = dict(runtime_env or {})
            self._env_last_used[want] = time.time()
        acq = getattr(self, "_acquirers_by_env", None)
        if acq is None:
            acq = self._acquirers_by_env = {}
        acq[want] = acq.get(want, 0) + 1
        t0 = asyncio.get_event_loop().time()
        deadline = t0 + self.config.worker_start_timeout_s
        first_pass = True
        try:
            while True:
                match = next((w for w in self._idle_q
                              if w.env_hash == want), None)
                if match is not None:
                    self._idle_q.remove(match)
                    if match.state == "idle":
                        if first_pass or match.recycled:
                            # Warm path: the worker either existed
                            # before the request (pool hit) or was
                            # handed back by a finishing lease — no
                            # fork was paid either way.
                            self._pool_adoptions += 1
                        else:
                            # Waited out a real process spawn.
                            self._note_cold_spawn()
                        self._startup_hist.observe(
                            asyncio.get_event_loop().time() - t0,
                            tags={"phase": "adopt"})
                        self._kick_refill()
                        return match
                    continue
                first_pass = False
                starting = getattr(self, "_starting_by_env", {}) \
                    .get(want, 0)
                # Actor-dedicated workers live outside the pool cap —
                # the cap bounds the REUSABLE task pool; actors scale
                # to memory (OOM monitor guards), matching the
                # reference where maximum_startup_concurrency limits
                # spawn rate, not actor count (ref: worker_pool.cc).
                active = sum(1 for w in self.workers.values()
                             if w.state != "actor") \
                    + self._starting_workers
                if starting < acq[want]:
                    if active >= self._max_workers():
                        # Pool full of mismatched-env workers: retire an
                        # idle one to make room (ref: worker_pool.cc
                        # idle-worker eviction on env mismatch).
                        victim = next((w for w in self._idle_q
                                       if w.env_hash != want), None)
                        if victim is not None:
                            self._idle_q.remove(victim)
                            await self._retire_worker(victim)
                            active -= 1
                    if active < self._max_workers():
                        self._spawn_worker(runtime_env)
                self._worker_ready.clear()
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    return None
                try:
                    await asyncio.wait_for(self._worker_ready.wait(),
                                           remaining)
                except asyncio.TimeoutError:
                    return None
        finally:
            acq[want] -= 1

    async def _retire_worker(self, w: WorkerEntry) -> None:
        w.state = "dead"
        self.workers.pop(w.worker_id, None)
        try:
            cli = RpcClient(w.addr, connect_timeout=2.0)
            await asyncio.wait_for(cli.call("exit", {}), timeout=5.0)
            await cli.close()
        except (RpcError, asyncio.TimeoutError, OSError):
            if w.proc is not None:
                w.proc.terminate()

    # ------------------------------------------------ warm prestart pool
    def _prestart_target(self) -> int:
        n = self.config.worker_prestart
        if n < 0:
            # Auto: the node's CPUs — bounded by the PHYSICAL core
            # count, not just the declared resource total (test
            # clusters declare num_cpus=4 on 1-core hosts; prestarting
            # more processes than cores only adds fork contention).
            n = min(int(self.total.get("CPU")), os.cpu_count() or 1)
        return max(0, min(n, self._max_workers()))

    def _prestart_burst(self) -> int:
        n = self.config.worker_prestart_burst
        if n <= 0:
            n = max(2, int(self.total.get("CPU")))
        return n

    def _note_cold_spawn(self) -> None:
        """A lease had to wait for a worker spawn (pool miss/empty):
        the fallback the prestart pool exists to avoid.  Windowed for
        the doctor's pool-exhaustion check."""
        self._pool_cold_spawns += 1
        now = time.time()
        self._cold_spawn_ts.append(now)
        if len(self._cold_spawn_ts) > 1024:
            del self._cold_spawn_ts[:512]

    def _cold_spawns_in_window(self, window_s: float = 60.0) -> int:
        cutoff = time.time() - window_s
        return sum(1 for ts in self._cold_spawn_ts if ts >= cutoff)

    def _kick_refill(self) -> None:
        self._refill_wakeup.set()

    def _pool_counts(self, env_hash: str) -> Tuple[int, int, int]:
        """(idle, starting, leased) non-actor workers of one env hash."""
        idle = sum(1 for w in self._idle_q if w.env_hash == env_hash
                   and w.state == "idle")
        starting = getattr(self, "_starting_by_env", {}) \
            .get(env_hash, 0)
        leased = sum(1 for w in self.workers.values()
                     if w.state == "leased" and w.env_hash == env_hash)
        return idle, starting, leased

    async def _prestart_refill_loop(self) -> None:
        """Keep the prestart pool at target: kicked after every
        adoption, and ticking on ``worker_prestart_refill_ms`` to heal
        losses (worker death, env churn).  The refill respects the
        drain state — a DRAINING node's pool is killed, not warmed."""
        period = max(self.config.worker_prestart_refill_ms, 10) / 1000.0
        # Boot warmup: let the agent finish registration/heartbeat
        # setup before forking the first prestart wave — the pool is
        # a steady-state optimization, not a boot-path dependency
        # (and on small shared hosts a fork herd at agent start
        # races the agent's own ready handshake for CPU).
        try:
            await asyncio.wait_for(self._shutdown.wait(), 1.0)
            return
        except asyncio.TimeoutError:
            pass
        while not self._shutdown.is_set():
            try:
                await asyncio.wait_for(self._refill_wakeup.wait(),
                                       period)
            except asyncio.TimeoutError:
                pass
            self._refill_wakeup.clear()
            if self._shutdown.is_set() or self._draining:
                continue
            try:
                self._refill_pool_once()
            except Exception as e:  # noqa: BLE001 — loop must survive
                # A failed fork (EAGAIN/ENOMEM under load) costs one
                # tick, never the loop: a dead refill loop would
                # silently turn every future creation into a cold
                # spawn for the agent's lifetime.
                logger.warning("prestart refill failed: %r", e)

    def _refill_pool_once(self) -> None:
        target = self._prestart_target()
        if target <= 0:
            return
        now = time.time()
        # Expire stale warm envs: drop their specs AND retire their
        # already-prestarted idle workers — default-env requests can
        # never adopt a mismatched env hash, so without this the
        # orphaned interpreters would hold RSS (and count against
        # max_workers room) for the agent's lifetime.
        ttl = self.config.worker_prestart_env_ttl_s
        for h in [h for h, ts in self._env_last_used.items()
                  if now - ts > ttl]:
            self._env_last_used.pop(h, None)
            self._env_specs.pop(h, None)
            for w in [w for w in self._idle_q
                      if w.env_hash == h and w.state == "idle"]:
                self._idle_q.remove(w)
                spawn_task(self._retire_worker(w))
        targets = warm_env_targets(now, target, self._env_last_used,
                                   ttl)
        pending = len(getattr(self, "_pending_spawns", {}))
        burst = self._prestart_burst()
        active = sum(1 for w in self.workers.values()
                     if w.state != "actor") + self._starting_workers
        for env_hash, env_target in targets.items():
            idle, starting, leased = self._pool_counts(env_hash)
            n = pool_plan(
                target=env_target, idle=idle, starting=starting,
                leased=leased, pending_spawns=pending, burst=burst,
                max_workers=self._max_workers(), active=active,
                draining=self._draining)
            renv = self._env_specs.get(env_hash) if env_hash else None
            for _ in range(n):
                self._spawn_worker(renv)
                pending += 1
                active += 1

    def _kill_prestart_pool(self) -> None:
        """DRAINING: idle pooled workers are pure warmth — kill them
        immediately so the grace window's CPU goes to migration work,
        and reap in-flight prestart spawns on arrival (the reap loop
        handles those when they register post-drain via _try_grant's
        refusal; unregistered ones die with the agent)."""
        idle, self._idle_q = self._idle_q, []
        for w in idle:
            w.state = "dead"
            self.workers.pop(w.worker_id, None)
            try:
                if w.proc is not None:
                    w.proc.kill()
                else:
                    os.kill(w.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        if idle:
            logger.info("drain: killed %d prestarted idle worker(s)",
                        len(idle))

    def _pool_stats_snapshot(self) -> Dict[str, Any]:
        idle, starting, leased = self._pool_counts("")
        idle_all = sum(1 for w in self._idle_q if w.state == "idle")
        hist_counts: Dict[str, int] = {}
        for s in self._startup_hist._snapshot().get("series", []):
            phase = (s.get("tags") or {}).get("phase", "?")
            hist_counts[phase] = int(s.get("hist", {}).get("count", 0))
        return {"node_id": self.node_id.hex(),
                "target": self._prestart_target(),
                "idle": idle, "idle_all": idle_all,
                "starting": starting, "leased": leased,
                "pending_spawns": len(getattr(self, "_pending_spawns",
                                              {})),
                "adoptions": self._pool_adoptions,
                "cold_spawns": self._pool_cold_spawns,
                "cold_spawns_60s": self._cold_spawns_in_window(),
                "spawned_total": self._spawned_total,
                "warm_envs": sorted(self._env_last_used),
                "draining": self._draining,
                "startup": hist_counts}

    async def pool_stats(self, _p=None):
        """The prestart pool's books (scale benches, `rt doctor`,
        tests): adoption vs cold-spawn counters, occupancy, and
        startup-phase sample counts."""
        return self._pool_stats_snapshot()

    # -------------------------------------- batched actor-started relay
    async def report_actor_started(self, p):
        """Relay a worker's actor hello to the controller, COALESCED:
        a creation fan-out (100 serve replicas, an RL env-runner
        fleet) becomes a handful of bulk ``actors_started`` RPCs on
        one persistent connection instead of a fresh controller dial
        per actor.  The worker still gets its per-actor reply (the
        kill-during-creation verdict rides it)."""
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._actor_started_buf.append((p, fut))
        if not self._actor_started_scheduled:
            self._actor_started_scheduled = True
            asyncio.get_event_loop().call_later(
                0.005, lambda: spawn_task(self._flush_actor_started()))
        return await fut

    async def _flush_actor_started(self) -> None:
        self._actor_started_scheduled = False
        items, self._actor_started_buf = self._actor_started_buf, []
        if not items:
            return
        try:
            r = await self._ctl.call(
                "actors_started", {"items": [p for p, _f in items]})
            results = r.get("results") or []
        except (RpcError, RemoteCallError) as e:
            # BOTH transport loss and a controller-side handler error
            # must resolve the futures — an escaped exception here
            # would leave every worker in the batch awaiting its
            # hello reply forever.
            for _p, fut in items:
                if not fut.done():
                    fut.set_exception(RpcError(
                        f"actor-started relay failed: {e}"))
            return
        for (_p, fut), res in zip(items, results):
            if not fut.done():
                fut.set_result(res if res is not None
                               else {"ok": False})
        # Length mismatch (controller bug): fail the unanswered rest.
        for _p, fut in items[len(results):]:
            if not fut.done():
                fut.set_exception(RpcError(
                    "actors_started reply shorter than request"))

    # ----------------------------------------------------------- scheduling
    def _kick_scheduler(self) -> None:
        spawn_task(self._drain_pending())

    async def _drain_pending(self) -> None:
        # FIFO with head-of-line skip for infeasible-now requests.
        still: List[_PendingLease] = []
        pending, self.pending = self.pending, []
        for req in pending:
            if req.future.done():
                continue
            granted = await self._try_grant(req.payload)
            if req.future.done():
                # Cancelled while we were granting (cancel_lease_request
                # resolved the future mid-await): give the lease back.
                if granted is not None:
                    lease = self.leases.get(granted["lease_id"])
                    if lease is not None:
                        self._release_lease(lease)
                continue
            if granted is None:
                still.append(req)
            else:
                req.future.set_result(granted)
        self.pending.extend(still)

    def _bundle_for(self, payload) -> Optional[_Bundle]:
        pg_id = payload.get("pg_id")
        if pg_id is None:
            return None
        idx = payload.get("bundle_index", -1)
        if idx >= 0:
            return self.bundles.get((pg_id, idx))
        for (bpid, _bidx), b in self.bundles.items():
            if bpid == pg_id and b.committed and \
                    b.resources.subtract(b.in_use).covers(
                        ResourceSet(payload["resources"])):
                return b
        return None

    def _job_usage_local(self) -> Dict[str, Dict[str, float]]:
        """Per-internal-job resource usage of this node's plain leases
        (PG-bound leases excluded: their bundles are accounted at the
        controller, and counting both would double-charge quotas)."""
        out: Dict[str, Dict[str, float]] = {}
        for lease in self.leases.values():
            if lease.pg_id is not None or not lease.job_id:
                continue
            acc = out.setdefault(lease.job_id, {})
            for k, v in lease.resources.amounts.items():
                acc[k] = acc.get(k, 0.0) + v
        return out

    def _quota_refuses(self, payload) -> bool:
        """Lease-grant-time quota enforcement: True when granting this
        plain lease would run its job over quota — the request stays
        QUEUED and grants as soon as the job's usage drops.  Usage =
        the controller's cluster-wide view minus what this node
        reported into it, plus this node's live books (so back-to-back
        local grants inside one heartbeat period can't overshoot)."""
        if payload.get("pg_id") is not None:
            return False  # bundle capacity was quota-charged at admission
        job_hex = payload.get("job_id") or ""
        view = self._job_view.get(job_hex)
        if view is None or not view.get("quota"):
            return False
        from ..util import multitenant

        used = multitenant.overlay_usage(
            view.get("used") or {},
            self._job_usage_reported.get(job_hex, {}),
            self._job_usage_local().get(job_hex, {}))
        return multitenant.quota_exceeded(view["quota"], used,
                                          dict(payload["resources"]))

    async def _try_grant(self, payload) -> Optional[Dict]:
        # A draining node grants NOTHING — not even queued requests
        # that predate the drain (they are redirected by _begin_drain)
        # or actor restarts (the controller retries on a live node).
        if self._draining:
            return None
        if self._quota_refuses(payload):
            return None  # over quota: stay queued until usage drops
        # Reserve resources synchronously (no awaits) so concurrent grant
        # attempts can't double-spend, then await a worker and refund on
        # failure.
        demand = ResourceSet(dict(payload["resources"]))
        bundle = self._bundle_for(payload)
        if payload.get("pg_id") is not None:
            if bundle is None or not bundle.committed:
                return None  # bundle not ready yet; stay queued
            if not bundle.resources.subtract(bundle.in_use).covers(demand):
                return None
            bundle.in_use = bundle.in_use.add(demand)
        elif not self.available.covers(demand):
            return None
        else:
            self.available = self.available.subtract(demand)
        # Chip ids come from one host-wide ledger regardless of PG binding
        # (bundles reserve TPU *counts*; the ids are assigned at lease
        # time so TPU_VISIBLE_CHIPS isolation always holds).
        chip_ids: List[int] = []
        n_tpu = int(demand.get("TPU"))

        def _refund():
            if bundle is not None:
                bundle.in_use = bundle.in_use.subtract(demand)
            else:
                self.available = self.available.add(demand)
                self._clamp_available()
            self.free_chips.extend(chip_ids)

        if n_tpu > 0:
            if len(self.free_chips) < n_tpu:
                chip_ids = []
                _refund()
                return None  # chips pinned by blocked leases; stay queued
            chip_ids = self.free_chips[:n_tpu]
            self.free_chips = self.free_chips[n_tpu:]
        w = await self._acquire_worker(payload.get("runtime_env"))
        if w is None:
            _refund()
            return None
        owner_tag = ("" if payload.get("is_actor")
                     else payload.get("owner_tag") or "")
        if owner_tag and not self.server.has_peer(owner_tag):
            # The owner's connection vanished while we were granting
            # (e.g. killed mid worker spawn).  Recording the lease now
            # would strand it forever — the conn-lost sweep already ran
            # and found nothing to reclaim.  No await separates this
            # check from the record below, so the sweep and this guard
            # can never both miss.
            _refund()
            self._idle_q.append(w)
            self._worker_ready.set()
            self._kick_scheduler()
            return {"ok": False, "cancelled": True}
        lease = Lease(
            lease_id=next(self._lease_counter), resources=demand, worker=w,
            chip_ids=chip_ids, pg_id=payload.get("pg_id"),
            bundle_index=payload.get("bundle_index", -1),
            owner_tag=owner_tag, granted_ts=time.time(),
            job_id=payload.get("job_id") or "")
        w.state = "actor" if payload.get("is_actor") else "leased"
        w.lease_id = lease.lease_id
        if payload.get("job_id"):
            w.job_id = payload["job_id"]
        if payload.get("actor_id") is not None:
            w.actor_id = payload["actor_id"]
        self.leases[lease.lease_id] = lease
        return {"ok": True, "lease_id": lease.lease_id,
                "worker_addr": w.addr, "worker_id": w.worker_id,
                "chip_ids": chip_ids, "node_id": self.node_id}

    async def request_lease(self, p):
        r = await self._request_lease_inner(p)
        if r is None:  # every branch must answer; never reply None
            logger.error("request_lease fell through for %r", p)
            r = {"ok": False, "error": "internal: no lease decision"}
        return r

    async def _request_lease_inner(self, p):
        """Grant a worker lease, queue, or spill to another node (ref:
        node_manager.cc:1867 HandleRequestWorkerLease +
        hybrid_scheduling_policy.h)."""
        if self._draining:
            # Redirect new work to a live peer when the placement
            # allows it; affinity/PG-bound leases cannot move, so they
            # fail fast and the owner's retry machinery deals with it.
            if p.get("pg_id") is None and not p.get("no_spill"):
                target = await self._pick_remote(
                    ResourceSet(dict(p["resources"])),
                    p.get("strategy", "DEFAULT"), by_total=True)
                if target is not None:
                    return {"ok": False, "retry_at": target}
            return {"ok": False, "error": "node draining"}
        granted = await self._try_grant(p)
        if granted is not None:
            return granted
        demand = ResourceSet(dict(p["resources"]))
        # Spillback decision (not for PG-bound or affinity-bound leases).
        strategy = p.get("strategy", "DEFAULT")
        if p.get("pg_id") is None and not p.get("no_spill") \
                and strategy in ("DEFAULT", "SPREAD"):
            target = await self._pick_remote(demand, strategy)
            if target is not None:
                return {"ok": False, "retry_at": target}
        if not self.total.covers(demand) and p.get("pg_id") is None:
            # This node can never run it.  Infeasibility is a CLUSTER
            # property (ref: cluster_task_manager.h:42 infeasible queue):
            # forward to any node whose TOTAL covers the demand — its
            # available may just be stale in the controller view — and
            # only error when no such node exists.  Affinity-bound and
            # hop-capped leases (no_spill) must NOT be forwarded: running
            # elsewhere would violate the placement constraint.
            if not p.get("no_spill") and strategy in ("DEFAULT", "SPREAD"):
                target = await self._pick_remote(demand, strategy,
                                                 by_total=True)
                if target is not None:
                    return {"ok": False, "retry_at": target}
                if self.config.autoscaling_enabled:
                    # Hold the request and surface it as demand; the
                    # autoscaler bin-packs held demands into new nodes
                    # (ref: cluster_task_manager.h infeasible queue +
                    # autoscaler LoadMetrics).  Re-probe for a capable
                    # node until one joins or the request times out.
                    return await self._await_feasible(p, demand, strategy)
            return {"ok": False,
                    "infeasible": True,
                    "error": f"resources {demand.amounts} can never be "
                             f"satisfied by any alive node "
                             f"(this node total {self.total.amounts})"}
        # Feasible here eventually: queue until resources free up.
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self.pending.append(_PendingLease(p, fut))
        timeout = p.get("queue_timeout") or 3600.0
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return {"ok": False, "error": "lease queue timeout"}

    async def _await_feasible(self, p, demand: ResourceSet,
                              strategy: str):
        rec = dict(demand.amounts)
        infeasible = getattr(self, "_infeasible", None)
        if infeasible is None:
            infeasible = self._infeasible = []
        infeasible.append(rec)
        rid = p.get("request_id")
        holds = getattr(self, "_infeasible_holds", None)
        if holds is None:
            holds = self._infeasible_holds = {}
        if rid:
            holds[rid] = rec
            hold_owners = getattr(self, "_hold_owner_tags", None)
            if hold_owners is None:
                hold_owners = self._hold_owner_tags = {}
            hold_owners[rid] = p.get("owner_tag") or ""
        deadline = asyncio.get_event_loop().time() + \
            (p.get("queue_timeout") or 3600.0)
        try:
            while asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.5)
                if rid and rid not in holds:
                    # cancel_lease_request yanked the hold: stop
                    # advertising demand for a task nobody wants.
                    return {"ok": False, "cancelled": True}
                if self.total.covers(demand):
                    # A hot-added local resource (not typical) — requeue.
                    return {"ok": False, "retry_at": self.server.address}
                target = await self._pick_remote(demand, strategy,
                                                 by_total=True)
                if target is not None:
                    return {"ok": False, "retry_at": target}
            return {"ok": False, "error": "lease queue timeout "
                                          "(demand never became feasible)"}
        finally:
            infeasible.remove(rec)
            if rid:
                holds.pop(rid, None)
                getattr(self, "_hold_owner_tags", {}).pop(rid, None)

    async def _pick_remote(self, demand: ResourceSet,
                           strategy: str,
                           by_total: bool = False) -> Optional[str]:
        """Hybrid policy: stay local under the utilization threshold, else
        pick the best remote with available capacity (ref:
        policy/hybrid_scheduling_policy.h:29-50).  ``by_total`` relaxes
        the filter to nodes whose total capacity covers the demand — used
        for demands this node can never satisfy, where the target should
        queue rather than reject."""
        local_util = self.available.utilization(self.total)
        if not by_total and strategy == "DEFAULT" and \
                not self._draining and \
                local_util < self.config.scheduler_spread_threshold \
                and self.total.covers(demand):
            return None  # queue locally; we're not saturated
        try:
            view = await self._ctl.call("resource_view", {})
        except RpcError:
            return None
        candidates = []
        for nid, info in view.items():
            if nid == self.node_id:
                continue
            avail = ResourceSet(dict(info["available"]))
            total = ResourceSet(dict(info["total"]))
            if (total if by_total else avail).covers(demand):
                candidates.append((avail.utilization(total), str(nid.hex()),
                                   info["agent_addr"]))
        if not candidates:
            return None
        candidates.sort()
        if strategy == "SPREAD":
            return candidates[0][2]
        # DEFAULT: only spill if we cannot serve now and someone can.
        # A DRAINING node can never serve — its free capacity is a
        # mirage (grants are refused), so the redirect must fire even
        # when available covers the demand, or a lightly-loaded
        # draining node hard-fails every request aimed at it.
        if self._draining or not self.available.covers(demand):
            return candidates[0][2]
        return None

    def _release_lease(self, lease: Lease, worker_back: bool = True) -> None:
        if lease.lease_id not in self.leases:
            return
        del self.leases[lease.lease_id]
        bundle = None
        if lease.pg_id is not None:
            bundle = self.bundles.get((lease.pg_id, lease.bundle_index))
            if bundle is None:
                for key, b in self.bundles.items():
                    if key[0] == lease.pg_id and \
                            b.in_use.covers(lease.resources):
                        bundle = b
                        break
        if bundle is not None:
            try:
                bundle.in_use = bundle.in_use.subtract(lease.resources)
            except ValueError:
                bundle.in_use = ResourceSet()
            if lease.blocked:
                # Undo the node-pool CPU credited at block time: the
                # bundle accounting above is the only release a PG
                # lease gets, so the credit would otherwise leak
                # phantom CPU into the pool forever.
                part = self._blockable_part(lease.resources)
                self.available = ResourceSet({
                    **self.available.amounts,
                    "CPU": self.available.get("CPU")
                    - part.get("CPU")})
        elif lease.blocked:
            # CPU was already re-credited at block time; return the rest.
            rest = lease.resources.subtract(
                self._blockable_part(lease.resources))
            self.available = self.available.add(rest)
            self._clamp_available()
        else:
            self.available = self.available.add(lease.resources)
            self._clamp_available()
        self.free_chips.extend(lease.chip_ids)
        w = lease.worker
        w.lease_id = None
        if worker_back and w.state == "leased":
            w.state = "idle"
            w.actor_id = None
            w.recycled = True
            self._idle_q.append(w)
            self._worker_ready.set()
        self._kick_scheduler()

    def _clamp_available(self) -> None:
        for k, cap in self.total.amounts.items():
            if self.available.amounts.get(k, 0.0) > cap:
                self.available.amounts[k] = cap

    def _on_owner_conn_lost(self, tag: str) -> None:
        """A registered peer's connection dropped.  If that peer owns
        leases or queued lease requests, schedule a grace-delayed
        reclamation — a dead owner can never return them, and the
        stranded workers would hold their resources forever."""
        if not tag:
            return
        # Stamp the disconnect time: the lease ledger reports
        # "owner disconnected for N seconds" from THIS moment, not
        # from whenever `rt list leases` first happens to look.
        lost_ts = self._owner_conn_lost_ts
        lost_ts[tag] = time.time()
        if len(lost_ts) > 1024:  # bound under owner churn
            oldest = min(lost_ts, key=lost_ts.get)
            lost_ts.pop(oldest, None)
        owns = any(l.owner_tag == tag for l in self.leases.values()) \
            or any(req.payload.get("owner_tag") == tag
                   for req in self.pending) \
            or tag in getattr(self, "_hold_owner_tags", {}).values()
        watching = getattr(self, "_reclaim_watch", None)
        if watching is None:
            watching = self._reclaim_watch = set()
        if owns and tag not in watching:
            watching.add(tag)
            spawn_task(self._reclaim_owner_leases(tag))

    async def _await_owner_death(self, tag: str,
                                 grace_s: float) -> bool:
        """True once the owner behind ``tag`` is confirmed gone, False
        if it reconnected.  rt-<pid> owners are processes on THIS node
        (only a runtime talking to its local agent uses that tag), so
        their liveness is checked directly — and re-checked on a slow
        cadence while the process lives, because the reclaim trigger is
        edge-based (the connection already dropped; if the owner dies
        later WITHOUT reconnecting, no further event fires).  rt-peer-*
        owners are remote; for them the grace window is the only
        signal, so a transient cross-node drop CAN cost a live owner
        its leased workers — that degrades to the worker_failed path
        (the owner's submit loop resubmits the failed task), a bounded
        retry, versus the forever-leak reclaiming too late would be."""
        local_pid = (int(tag[3:])
                     if tag.startswith("rt-") and tag[3:].isdigit()
                     else None)
        while True:
            await asyncio.sleep(grace_s)
            if self.server.has_peer(tag):
                return False
            if local_pid is None:
                return True
            try:
                os.kill(local_pid, 0)
            except ProcessLookupError:
                return True
            except PermissionError:
                return False  # pid exists (other user): not ours
            if not any(l.owner_tag == tag
                       for l in self.leases.values()):
                return False  # nothing left to watch for
            grace_s = 10.0  # alive local owner: keep watching

    async def _reclaim_owner_leases(self, tag: str,
                                    grace_s: float = 3.0) -> None:
        """After a grace window (a transient reconnect re-registers the
        tag on the owner's next call), free every lease the dead owner
        still holds.  The leased workers are KILLED, not recycled: the
        owner may have had a push in flight, and a worker with orphaned
        work must not re-enter the idle pool (same rationale as
        return_lease's worker_failed path)."""
        try:
            dead = await self._await_owner_death(tag, grace_s)
        finally:
            getattr(self, "_reclaim_watch", set()).discard(tag)
        if not dead:
            return  # owner reconnected; its leases are still live
        # Cancel queued + autoscaler-held lease requests from the dead
        # owner (a held infeasible demand would otherwise keep driving
        # the autoscaler for up to queue_timeout).
        hold_owners = getattr(self, "_hold_owner_tags", {})
        for rid in [r for r, t in list(hold_owners.items())
                    if t == tag]:
            getattr(self, "_infeasible_holds", {}).pop(rid, None)
            hold_owners.pop(rid, None)
        for req in list(self.pending):
            if req.payload.get("owner_tag") == tag \
                    and not req.future.done():
                req.future.set_result({"ok": False, "cancelled": True})
                try:
                    self.pending.remove(req)
                except ValueError:
                    pass
        stale = [l for l in self.leases.values() if l.owner_tag == tag]
        for lease in stale:
            logger.warning(
                "reclaiming lease %s (worker pid %s): owner %s is gone",
                lease.lease_id, lease.worker.pid, tag)
            self._release_lease(lease, worker_back=False)
            w = lease.worker
            w.state = "dead"
            self.workers.pop(w.worker_id, None)
            try:
                if w.proc is not None:
                    w.proc.kill()
                else:
                    os.kill(w.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    async def cancel_lease_request(self, p):
        """Yank a queued-but-ungranted lease request (task cancellation;
        ref: node_manager CancelWorkerLease)."""
        rid = p.get("request_id")
        for req in list(self.pending):
            if req.payload.get("request_id") == rid \
                    and not req.future.done():
                req.future.set_result(
                    {"ok": False, "cancelled": True})
                self.pending.remove(req)
                return {"ok": True, "cancelled": True}
        holds = getattr(self, "_infeasible_holds", {})
        if rid in holds:
            # Held in _await_feasible (cluster-infeasible demand waiting
            # for the autoscaler): drop the hold; the waiter notices
            # within its poll tick.
            del holds[rid]
            return {"ok": True, "cancelled": True}
        return {"ok": True, "cancelled": False}

    async def return_lease(self, p):
        lease = self.leases.get(p["lease_id"])
        if lease is not None:
            if p.get("worker_failed"):
                # The owner's push to this worker failed: free the
                # resources but do NOT recycle the worker — kill it so
                # the reap loop confirms death (a wedged-but-alive
                # worker must not re-enter the idle pool).
                self._release_lease(lease, worker_back=False)
                w = lease.worker
                w.state = "dead"
                self.workers.pop(w.worker_id, None)
                try:
                    if w.proc is not None:
                        w.proc.kill()
                    else:
                        os.kill(w.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            else:
                self._release_lease(lease)
        return {"ok": True}

    async def lease_status(self, p):
        lease = self.leases.get(p["lease_id"])
        if lease is None:
            return {"alive": False}
        return {"alive": lease.worker.state != "dead",
                "worker_addr": lease.worker.addr}

    # ------------------------------------------------ lease ledger view
    async def report_lease_pool(self, p):
        """Owner-side pooled-lease state (notify, sweeper cadence):
        per-lease in-flight pipeline depth, so `rt list leases` can
        show how deep each held lease is pipelined — state only the
        owner knows (pushes go owner -> worker directly)."""
        depths = self._owner_lease_depths
        now = time.time()
        owner = p.get("owner")
        for lid, depth in (p.get("leases") or {}).items():
            depths[int(lid)] = (owner, int(depth), now)
        # Prune on the report cadence, not just in list_leases (which
        # only runs when an operator asks): returned leases stop
        # refreshing and would otherwise accumulate forever.
        self._prune_lease_depths(now)
        return {"ok": True}

    def _prune_lease_depths(self, now: float) -> None:
        depths = self._owner_lease_depths
        for lid in [k for k, (_o, _d, ts) in depths.items()
                    if now - ts > 5.0]:
            depths.pop(lid, None)

    async def list_leases(self, _p):
        """The node's lease ledger + demand vector (scheduler
        explainability: what is held, by whom, how deep, how stale —
        the state that previously was only visible in agent logs)."""
        now = time.time()
        depths = self._owner_lease_depths
        self._prune_lease_depths(now)
        # Disconnect AGE per lease: seeded from the connection-lost
        # hook's stamp, so one `rt doctor` run sees the true age — a
        # momentary re-dial must not read as a dead owner, but an
        # owner that died an hour ago must not read as fresh either.
        disc_since = self._owner_disc_since
        lost_ts = self._owner_conn_lost_ts
        leases = []
        for lease in self.leases.values():
            w = lease.worker
            connected = (not lease.owner_tag
                         or self.server.has_peer(lease.owner_tag))
            if connected:
                disc_since.pop(lease.lease_id, None)
                lost_ts.pop(lease.owner_tag, None)
            else:
                disc_since.setdefault(
                    lease.lease_id,
                    lost_ts.get(lease.owner_tag, now))
            ent = {
                "lease_id": lease.lease_id,
                "owner_tag": lease.owner_tag,
                "owner_connected": connected,
                "owner_disconnected_s": (
                    now - disc_since[lease.lease_id]
                    if not connected else 0.0),
                "worker_pid": w.pid,
                "worker_state": w.state,
                "resources": dict(lease.resources.amounts),
                "chip_ids": list(lease.chip_ids),
                "blocked": lease.blocked,
                "pg_id": (lease.pg_id.hex()
                          if lease.pg_id is not None else None),
                "bundle_index": lease.bundle_index,
                "age_s": (now - lease.granted_ts
                          if lease.granted_ts else 0.0),
                # Per-job attribution: the submitted-job id when the
                # heartbeat view can resolve it, else the internal
                # driver job hex.
                "job": (self._job_view.get(lease.job_id, {})
                        .get("job") or lease.job_id[:12]),
            }
            dep = depths.get(lease.lease_id)
            if dep is not None:
                ent["pipeline_depth"] = dep[1]
            leases.append(ent)
        for lid in [k for k in disc_since if k not in self.leases]:
            disc_since.pop(lid, None)  # lease returned/reclaimed
        pending = [{"resources": dict(req.payload["resources"]),
                    "strategy": req.payload.get("strategy", "DEFAULT"),
                    "owner_tag": req.payload.get("owner_tag", ""),
                    "age_s": now - req.enqueue_time}
                   for req in self.pending]
        return {"node_id": self.node_id.hex(),
                "leases": leases, "pending": pending,
                "demand": self._demand_vector(),
                "available": dict(self.available.amounts),
                "total": dict(self.total.amounts),
                # Pool occupancy rides the ledger so `rt doctor`'s
                # pool-exhaustion check needs no extra fan-out.
                "worker_pool": self._pool_stats_snapshot()}

    async def report_collective_entries(self, p):
        """Relay a worker's inflight collective-entry stamps to the
        controller (gang watchdog input; same relay report_spans
        rides)."""
        p.setdefault("node_id", self.node_id.hex())
        try:
            await self._ctl.call("collective_entries", p)
        except RpcError:
            pass
        return {"ok": True}

    # -------------------------------------------- blocked-worker CPU credit
    @staticmethod
    def _blockable_part(resources: ResourceSet) -> ResourceSet:
        """Only CPU is released while blocked in get() — accelerators stay
        assigned (their chips are still mapped into the worker), matching
        the reference releasing only CPU for blocked workers."""
        return ResourceSet({"CPU": resources.get("CPU")})

    async def task_blocked(self, p):
        """A worker blocked in get(): return its CPU so nested tasks can
        schedule (ref: the reference releases CPU for blocked workers in
        local_task_manager).  PG-bound leases credit the NODE pool too:
        a gang whose placement group covers the whole node would
        otherwise starve every non-PG lease forever — e.g. a training
        gang blocked pushing to a result-queue actor that can never
        schedule (the reference likewise releases blocked workers' CPU
        regardless of placement-group binding)."""
        lease = self.leases.get(p["lease_id"])
        if lease is not None and not lease.blocked:
            lease.blocked = True
            self.available = self.available.add(
                self._blockable_part(lease.resources))
            self._clamp_available()
            self._kick_scheduler()
        return {"ok": True}

    async def task_unblocked(self, p):
        lease = self.leases.get(p["lease_id"])
        if lease is not None and lease.blocked:
            lease.blocked = False
            # May oversubscribe briefly; clamped in heartbeat view.
            part = self._blockable_part(lease.resources)
            self.available = ResourceSet({
                **self.available.amounts,
                "CPU": self.available.get("CPU") - part.get("CPU")})
        return {"ok": True}

    # -------------------------------------------------------- object plane
    async def register_object(self, p):
        """Producer-side registration.  The producer's copy is the primary
        copy: pinned until distributed ref counting frees the object, so
        LRU pressure can never delete the only live copy (ref:
        object_lifecycle_manager.h primary-copy pinning)."""
        oid, size = p["object_id"], p["size"]
        if oid in self._early_released:
            # The owner's fast release overtook this registration
            # (different channels): registering now would create a
            # ghost pinned entry nobody will ever delete.
            self._early_released.discard(oid)
            return {"ok": True}
        evicted = self.directory.register(
            oid, size, primary=p.get("primary", True))
        self._queue_loc_update("add", (oid, size))
        for vid in evicted:
            self._queue_loc_update("remove", vid)
        return {"ok": True}

    def _queue_loc_update(self, kind: str, item) -> None:
        """Buffer one ordered location add/remove for the controller;
        a short flush window coalesces a put/release burst into one
        bulk notify (pull discovery polls with >=20 ms backoff, so a
        5 ms publication delay is invisible — but ~4 control frames
        per object put become amortized to ~zero)."""
        self._loc_buf.append((kind, item))
        if not self._loc_flush_scheduled:
            self._loc_flush_scheduled = True
            asyncio.get_event_loop().call_later(0.005, self._loc_flush)

    def _loc_flush(self) -> None:
        self._loc_flush_scheduled = False
        if self._loc_send_inflight or not self._loc_buf:
            # One acked send in flight at a time: concurrent sends
            # could complete out of order across a reconnect and
            # replay an "add" after its "remove" (ghost entry).
            return
        updates, self._loc_buf = self._loc_buf, []
        self._loc_send_inflight = True

        def _reschedule(delay: float) -> None:
            if not self._loc_flush_scheduled:
                self._loc_flush_scheduled = True
                asyncio.get_event_loop().call_later(
                    delay, self._loc_flush)

        async def _send():
            try:
                await asyncio.wait_for(
                    self._ctl.call("update_locations", {
                        "node_id": self.node_id, "updates": updates}),
                    10.0)
            except (RpcError, asyncio.TimeoutError):
                # Controller reconnect window: REQUEUE (ordered, at the
                # head) and retry after a beat — a dropped batch would
                # permanently hide these copies from cross-node gets
                # (plain puts have no lineage to reconstruct from).
                # Duplicate replays are idempotent controller-side.
                self._loc_buf[0:0] = updates
                if len(self._loc_buf) > 100_000:
                    dropped = len(self._loc_buf) - 100_000
                    del self._loc_buf[:dropped]
                    logger.warning(
                        "location-update backlog overflow: dropped %d "
                        "oldest updates during controller outage — "
                        "some copies may stay unpublished", dropped)
                self._loc_send_inflight = False
                _reschedule(0.5)
                return
            self._loc_send_inflight = False
            if self._loc_buf:
                _reschedule(0.005)

        asyncio.ensure_future(_send())

    async def objects_exist(self, p):
        """Bulk local-directory probe (wait() fallback for objects whose
        controller publication failed or lagged)."""
        return {oid: self.directory.lookup(oid) is not None
                for oid in p["object_ids"]}

    async def object_exists(self, p):
        ent = self.directory.lookup(p["object_id"])
        return {"exists": ent is not None,
                "size": ent.size if ent else 0}

    async def pull_object(self, p):
        """Ensure the object is in the local store; returns its size.
        (ref: pull_manager.h:52 — location lookup then chunked fetch.)"""
        oid = p["object_id"]
        ent = self.directory.lookup(oid)
        if ent is not None:
            return await self._local_ready(oid, ent)
        if p.get("fail_fast"):
            # Recovery probes never coalesce: they must answer "gone"
            # immediately, not wait behind a long-polling pull (and a
            # normal pull must not inherit a probe's instant failure).
            r = await self._do_pull(oid, p.get("timeout", 30.0),
                                    fail_fast=True)
            if r.get("ok"):
                self._grant_read_window(oid)
            return r
        inflight = self._pull_inflight.get(oid)
        if inflight is not None:
            result = await asyncio.shield(inflight)
            if result.get("ok"):
                self._grant_read_window(oid)
            return result
        fut = asyncio.get_event_loop().create_future()
        self._pull_inflight[oid] = fut
        try:
            result = await self._do_pull(oid, p.get("timeout", 30.0))
            if not fut.done():
                fut.set_result(result)
            if result.get("ok"):
                self._grant_read_window(oid)
            return result
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)
            raise
        finally:
            self._pull_inflight.pop(oid, None)

    async def _do_pull(self, oid: ObjectID, timeout: float,
                       fail_fast: bool = False) -> Dict:
        """``fail_fast`` returns "no locations" immediately instead of
        polling — the owner uses it to decide whether to reconstruct the
        object from lineage rather than wait out the timeout."""
        deadline = asyncio.get_event_loop().time() + timeout
        delay = 0.02
        while True:
            try:
                loc = await self._ctl.call("locate_object",
                                           {"object_id": oid})
            except RpcError:
                loc = None
            if loc and loc["nodes"]:
                for cand in loc["nodes"]:
                    if cand["node_id"] == self.node_id:
                        continue
                    addr = cand["agent_addr"]
                    cli = self._peer_agents.get(addr)
                    if cli is None or not cli.connected:
                        cli = RpcClient(addr, tag=f"agent-pull-{self.node_id.hex()[:6]}")
                        try:
                            await cli.connect()
                        except RpcError:
                            continue
                        self._peer_agents[addr] = cli
                    size_hint = loc.get("size", 0)
                    chunk = self.config.object_transfer_chunk_bytes
                    try:
                        if size_hint and size_hint > chunk:
                            n = await self._pull_chunked(
                                cli, oid, size_hint, chunk)
                        else:
                            data = await cli.call("fetch_raw",
                                                  {"object_id": oid})
                            if data is None:
                                continue
                            self.store.put_raw(oid, data)
                            n = len(data)
                    except RpcError:
                        continue
                    if n is None:
                        continue
                    # Pulled replica = secondary copy, LRU-evictable.
                    # Publication rides the ordered update queue so it
                    # can never be overtaken by (or overtake) another
                    # path's add/remove for the same oid.
                    evicted = self.directory.register(oid, n)
                    self._queue_loc_update("add", (oid, n))
                    for vid in evicted:
                        self._queue_loc_update("remove", vid)
                    return {"ok": True, "size": n}
            # Re-check local (producer may have just sealed here).
            ent = self.directory.lookup(oid)
            if ent is not None:
                return await self._local_ready(oid, ent)
            if fail_fast and not (loc and loc["nodes"]):
                return {"ok": False, "error": "no locations"}
            if asyncio.get_event_loop().time() > deadline:
                return {"ok": False, "error": "object not found"}
            await asyncio.sleep(delay)
            delay = min(delay * 1.5, 0.5)

    async def _local_ready(self, oid: ObjectID, ent) -> Dict:
        """Finalize a pull that found a local entry: restore from spill
        if needed, grant the read window, build the reply."""
        if ent.spilled:
            ok = await asyncio.get_event_loop().run_in_executor(
                None, self.directory.restore, oid)
            if not ok:
                return {"ok": False, "error": "spilled copy lost"}
        self._grant_read_window(oid)
        return {"ok": True, "size": ent.size}

    def _grant_read_window(self, oid: ObjectID,
                           ttl: float = 10.0) -> None:
        """Short transient read pin after a successful pull: the caller
        maps the segment out-of-band, and under heavy spill churn the
        object must not be re-spilled in that window (otherwise
        concurrent readers thrash restore/spill and starve).  Windows
        allow transient over-capacity; expiry sheds the excess."""
        self.directory.read_pin(oid)
        loop = asyncio.get_event_loop()

        def _expire():
            self.directory.read_unpin(oid)
            n, used, cap = self.directory.stats()
            if used > cap:
                loop.run_in_executor(
                    None, self.directory._shed_pressure, None)

        loop.call_later(ttl, _expire)

    async def _pull_chunked(self, cli, oid: ObjectID, size: int,
                            chunk: int):
        """Assemble a large object from bounded chunk RPCs, then seal it
        locally (ref: pull_manager.h:52 chunked object reads — chunking
        bounds the per-RPC frame, so no giant pickle frame ever crosses
        the wire).  Up to ``pull_parallelism`` chunk fetches ride the
        wire concurrently (a fixed worker pool over the offset sequence
        — the pool size IS the in-flight window, so backpressure is
        structural): the source overlaps its per-chunk store/disk reads
        across executor threads while earlier chunks are in transit,
        instead of paying one RTT + one read per chunk serially.
        Assembly happens in a host buffer, NOT directly in the
        destination segment: on a shared-/dev/shm test topology the
        destination name aliases the source segment, and an in-place
        create would clobber the bytes mid-read.  Returns the byte
        count, or None if the source lost its copy."""
        buf = bytearray(size)
        offsets = iter(range(0, size, chunk))
        lost = False
        failure: Optional[BaseException] = None

        async def _fetch_worker():
            nonlocal lost, failure
            # Plain-iterator next() is atomic per worker turn (no await
            # between take and use), so offsets are claimed exactly once.
            for offset in offsets:
                if lost or failure is not None:
                    return  # a sibling failed: stop claiming chunks
                length = min(chunk, size - offset)
                try:
                    r = await cli.call("fetch_chunk", {
                        "object_id": oid, "offset": offset,
                        "length": length})
                except BaseException as e:  # noqa: BLE001 — re-raised
                    failure = e
                    return
                if r is None or len(r["data"]) < length:
                    lost = True  # copy vanished / source shrank
                    return
                buf[offset:offset + length] = r["data"]

        window = max(1, int(getattr(self.config, "pull_parallelism", 1)))
        n_chunks = (size + chunk - 1) // chunk
        workers = [asyncio.ensure_future(_fetch_worker())
                   for _ in range(min(window, n_chunks))]
        try:
            await asyncio.gather(*workers)
        finally:
            for w in workers:
                w.cancel()
        if failure is not None:
            raise failure  # RpcError -> caller tries the next location
        if lost:
            return None
        self.store.put_raw(oid, memoryview(buf))
        return size

    async def fetch_raw(self, p):
        oid = p["object_id"]
        ent = self.directory.lookup(oid)
        if ent is None:
            return None
        # Transient read pin: the peer's pull must not race local
        # eviction OR spilling.  Disk/shm copies run off the loop.
        self.directory.read_pin(oid)
        try:
            loop = asyncio.get_event_loop()
            if ent.spilled:
                # Serve straight from disk; no need to un-spill locally.
                return await loop.run_in_executor(
                    None, self.directory.read_spilled, oid)
            return await loop.run_in_executor(
                None, self.store.read_raw, oid, ent.size)
        except FileNotFoundError:
            return None
        finally:
            self.directory.read_unpin(oid)

    async def fetch_chunk(self, p):
        """One chunk of an object's packed bytes (ref: pull_manager.h:52
        chunked pulls / ObjectBufferPool) — large objects move as a
        sequence of bounded frames, not one giant one.  Returns
        {"data", "size"} or None if the copy vanished (the puller falls
        back to another location)."""
        oid = p["object_id"]
        ent = self.directory.lookup(oid)
        if ent is None:
            return None
        offset, length = p["offset"], p["length"]
        self.directory.read_pin(oid)
        try:
            loop = asyncio.get_event_loop()
            if ent.spilled:
                data = await loop.run_in_executor(
                    None, self.directory.read_spilled, oid, offset,
                    length)
                if data is None:
                    return None
            else:
                data = await loop.run_in_executor(
                    None, self.store.read_raw_slice, oid, offset,
                    length)
            return {"data": data, "size": ent.size}
        except FileNotFoundError:
            return None
        finally:
            self.directory.read_unpin(oid)

    async def delete_object(self, p):
        self.directory.delete(p["object_id"])

    async def owner_release_local(self, p):
        """Fast-path release from a local owner for a never-shared
        object (plain put whose ref was never pickled): the owner
        already freed the store bytes (eager local free); retire the
        directory entry and the published locations WITHOUT the
        controller owner_release/free_object round trip — no borrower
        or induced borrow can exist for it."""
        oid = p["object_id"]
        if self.directory.delete(oid):
            self._queue_loc_update("remove", oid)
        else:
            # Release overtook the registration (side channel vs main
            # connection): flag it so the late register is dropped
            # instead of resurrecting a ghost entry.  Bounded.
            self._early_released.add(oid)
            while len(self._early_released) > 4096:
                self._early_released.pop()
        return {"ok": True}

    async def store_stats(self, _p):
        n, used, cap = self.directory.stats()
        return {"objects": n, "used_bytes": used, "capacity_bytes": cap,
                **self.directory.spill_stats()}

    async def make_room(self, p):
        """Producer backpressure relief: evict/spill until the caller's
        byte need fits (ref: plasma CreateRequestQueue).  Spill IO is
        blocking — run off the RPC loop."""
        nbytes = int(p.get("bytes", 0))
        evicted = await asyncio.get_event_loop().run_in_executor(
            None, self.directory.make_room, nbytes)
        return {"ok": True, "evicted": len(evicted)}

    # -------------------------------------------------- placement bundles
    async def prepare_bundle(self, p):
        key = (p["pg_id"], p["bundle_index"])
        existing = self.bundles.get(key)
        if existing is not None:
            # Re-prepare of a bundle we still hold (controller retry /
            # reschedule): keep the reservation, don't double-subtract.
            return {"ok": True}
        demand = ResourceSet(dict(p["resources"]))
        if not self.available.covers(demand):
            return {"ok": False}
        self.available = self.available.subtract(demand)
        self.bundles[key] = _Bundle(
            pg_id=p["pg_id"], bundle_index=p["bundle_index"],
            resources=demand)
        return {"ok": True}

    async def commit_bundle(self, p):
        b = self.bundles.get((p["pg_id"], p["bundle_index"]))
        if b is None:
            return {"ok": False}
        b.committed = True
        self._kick_scheduler()
        return {"ok": True}

    async def return_bundle(self, p):
        b = self.bundles.pop((p["pg_id"], p["bundle_index"]), None)
        if b is not None:
            self.available = self.available.add(b.resources)
            self._clamp_available()
            self._kick_scheduler()
        return {"ok": True}

    async def preempt_pg_leases(self, p):
        """Job-preemption enforcement (controller-driven): SIGKILL the
        workers holding leases under this placement group's bundles.
        The deaths flow through the normal reap path — actor_died with
        the worker gone — so the owning trainer sees its gang fail
        AFTER the preemption notice it has been polling, classifies
        the loss as announced, and restarts from the checkpoint-on-
        notice.  Bundle reservations are returned separately by the
        controller's remove_placement_group pass."""
        pg_id = p["pg_id"]
        killed = []
        for lease in list(self.leases.values()):
            if lease.pg_id != pg_id:
                continue
            w = lease.worker
            try:
                if w.proc is not None:
                    w.proc.kill()
                else:
                    os.kill(w.pid, signal.SIGKILL)
                killed.append(w.pid)
            except (ProcessLookupError, PermissionError):
                pass
        if killed:
            logger.warning("preempted %d worker(s) of pg %s (%s)",
                           len(killed), pg_id.hex()[:12],
                           p.get("reason", ""))
        return {"ok": True, "killed": killed}

    # ------------------------------------------------------ actor lifecycle
    async def restart_actor(self, p):
        """Controller asks this node to host a restarted actor."""
        spec = p["spec"]
        granted = await self._try_grant({
            "resources": dict(spec.resources.amounts), "is_actor": True,
            "actor_id": spec.actor_id, "pg_id": None})
        if granted is None:
            return {"ok": False}

        def _undo():
            lease = self.leases.get(granted["lease_id"])
            if lease is not None:
                # Flip back to 'leased' so release re-queues the worker.
                if lease.worker.state == "actor":
                    lease.worker.state = "leased"
                    lease.worker.actor_id = None
                self._release_lease(lease)

        cli = RpcClient(granted["worker_addr"], tag="agent-restart")
        try:
            await cli.connect()
            r = await cli.call("create_actor", {
                "spec": spec, "chip_ids": granted["chip_ids"],
                "lease_id": granted["lease_id"], "is_restart": True})
            await cli.close()
            if not r.get("ok"):
                _undo()
                return {"ok": False}
            return {"ok": True}
        except RpcError:
            _undo()
            return {"ok": False}

    async def report_actor_failure(self, p):
        """Worker-side creation failure path (process still alive)."""
        try:
            await self._ctl.call("actor_died", p)
        except RpcError:
            pass
        return {"ok": True}

    async def kill_worker(self, p):
        target: Optional[WorkerEntry] = None
        if p.get("actor_id") is not None:
            for w in self.workers.values():
                if w.actor_id == p["actor_id"]:
                    target = w
                    break
        elif p.get("worker_id") is not None:
            target = self.workers.get(p["worker_id"])
        if target is not None and target.proc is not None:
            try:
                target.proc.kill()
            except Exception:
                pass
        elif target is not None:
            try:
                os.kill(target.pid, signal.SIGKILL)
            except Exception:
                pass
        return {"ok": target is not None}

    # -------------------------------------------------------------- admin
    async def drain(self, p=None):
        """Enter the DRAINING lifecycle state (operator `rt drain`,
        controller drain_node, or the autoscaler's idle reap).
        ``if_idle`` (the autoscaler's mode) refuses when leases are
        active, closing the race where a task is granted between the
        idle observation and the terminate (ref: DrainRaylet rejection
        path, node_manager.proto:407)."""
        p = p or {}
        if p.get("if_idle") and (self.leases or self.pending):
            return {"ok": False, "busy": True,
                    "leases": len(self.leases)}
        await self._begin_drain(
            reason=p.get("reason") or "drain requested",
            grace_s=p.get("grace_s") or self.config.preemption_grace_s,
            replace=p.get("replace", not p.get("if_idle", False)))
        return {"ok": True, "draining": True,
                "deadline": self._drain_deadline,
                "remaining_s": self._drain_remaining(),
                "node_id": self.node_id.hex()}

    def _drain_remaining(self) -> float:
        """Grace left before this node's drain deadline, in THIS
        host's clock-free terms — the form the deadline crosses hosts
        in (the receiver re-anchors it to its own clock)."""
        if not self._draining or not self._drain_deadline:
            return 0.0
        return max(self._drain_deadline - time.time(), 0.0)

    async def _begin_drain(self, reason: str, grace_s: float,
                           replace: bool = True,
                           shutdown_at_deadline: bool = False) -> None:
        """The drain state machine's single entry point: stop granting,
        stamp the deadline, redirect queued lease requests to live
        peers, and notify the controller immediately (the heartbeat
        would carry it anyway, but the grace window can be seconds —
        every one counts for the checkpoint-on-notice race)."""
        if self._draining:
            return  # already draining; first deadline stands
        self._draining = True
        self._drain_reason = reason
        self._drain_deadline = time.time() + max(grace_s, 0.0)
        self._drain_replace = replace
        # The prestart pool dies with the drain decision: warm idle
        # workers on a node about to die are wasted CPU/RSS, and the
        # refill loop checks _draining before every spawn.
        self._kill_prestart_pool()
        logger.warning("node DRAINING (%s): deadline in %.1fs, "
                       "%d lease(s) held, %d queued request(s)",
                       reason, grace_s, len(self.leases),
                       len(self.pending))
        if shutdown_at_deadline:
            # Preemption-notice drains mirror the real failure: the VM
            # dies at the deadline whether or not we are ready.
            asyncio.get_event_loop().call_later(
                max(grace_s, 0.0), lambda: spawn_task(self.shutdown()))
        # Proactively requeue queued work: resolve each pending lease
        # request with a redirect to a peer that could ever host it,
        # so owners re-request there instead of queueing into a node
        # about to die.  Placement-bound requests stay queued (they
        # cannot move; the controller reschedules the group on death).
        for req in list(self.pending):
            if req.future.done():
                continue
            payload = req.payload
            if payload.get("pg_id") is not None or \
                    payload.get("no_spill"):
                continue
            target = await self._pick_remote(
                ResourceSet(dict(payload["resources"])),
                payload.get("strategy", "DEFAULT"), by_total=True)
            if target is not None and not req.future.done():
                req.future.set_result({"ok": False, "retry_at": target})
                try:
                    self.pending.remove(req)
                except ValueError:
                    pass
        if self._ctl is None:
            return  # SIGTERM before registration: nothing to migrate
        try:
            await self._ctl.call("node_draining", {
                "node_id": self.node_id, "reason": reason,
                "deadline": self._drain_deadline,
                "remaining_s": self._drain_remaining(),
                "replace": replace})
        except RpcError:
            pass  # heartbeat mirrors the state within a period

    async def ping(self, _p):
        return {"ok": True, "node_id": self.node_id}

    async def list_workers(self, _p):
        """Worker inventory (chaos killers + debugging)."""
        return {"workers": [
            {"pid": w.pid, "state": w.state,
             "worker_id": w.worker_id.hex(),
             "actor_id": w.actor_id.hex() if w.actor_id else None}
            for w in self.workers.values()]}

    # ------------------------------------------------------------ log plane
    async def _log_monitor_loop(self) -> None:
        """Tail every worker's log file; publish new lines to the
        controller's worker_logs pubsub channel, job-tagged, so the
        submitting driver can print them (ref: _private/
        log_monitor.py:103 — per-node tailer, redesigned as an agent
        coroutine instead of a separate process)."""
        offsets: Dict[str, int] = {}
        # path -> (pid, worker_id hex, job_id); sticky so a dead
        # worker's final lines still drain with their last-known tags.
        meta: Dict[str, tuple] = {}
        # path -> consecutive no-data ticks while its worker is dead;
        # fully-drained dead entries are dropped so the tail set stays
        # bounded under worker churn.
        idle_dead: Dict[str, int] = {}
        # Dead workers' paths already fully drained: never re-tailed
        # (but still resolvable via _worker_log_paths for fetch).
        drained: set = set()
        while True:
            await asyncio.sleep(0.5)
            batch = []
            advances: List[tuple] = []  # (path, new_offset) on success
            live_pids = set()
            for w in self.workers.values():
                live_pids.add(w.pid)
                if w.log_path:
                    meta[w.log_path] = (w.pid, w.worker_id.hex(),
                                        w.job_id)
            for pid, path in getattr(self, "_worker_log_paths",
                                     {}).items():
                if path not in drained:
                    meta.setdefault(path, (pid, None, None))
            for path, (pid, wid, job) in list(meta.items()):
                try:
                    with open(path, "rb") as f:
                        f.seek(offsets.get(path, 0))
                        data = f.read(256 * 1024)
                except OSError:
                    data = b""
                # Only complete lines; partial tail re-read next tick.
                nl = data.rfind(b"\n") if data else -1
                if nl < 0:
                    if pid not in live_pids:
                        idle_dead[path] = idle_dead.get(path, 0) + 1
                        if idle_dead[path] >= 6:  # ~3s fully drained
                            # Drop from the TAILING set only; the
                            # pid→path mapping stays (it's tiny) so
                            # read_worker_log/list_worker_logs keep
                            # serving dead workers — the file outlives
                            # the process.
                            meta.pop(path, None)
                            offsets.pop(path, None)
                            idle_dead.pop(path, None)
                            drained.add(path)
                            # Bound retained dead entries under churn:
                            # keep the most recent 256 (insertion order
                            # of _worker_log_paths = spawn order).
                            wlp = getattr(self, "_worker_log_paths",
                                          {})
                            if len(drained) > 256:
                                for dpid, dpath in list(wlp.items()):
                                    if len(drained) <= 256:
                                        break
                                    if (dpath in drained
                                            and dpid not in live_pids):
                                        wlp.pop(dpid, None)
                                        drained.discard(dpath)
                    continue
                drained.discard(path)
                idle_dead.pop(path, None)
                lines = data[:nl].decode("utf-8",
                                         "replace").splitlines()
                advances.append((path, offsets.get(path, 0) + nl + 1))
                batch.append({"node_id": self.node_id.hex(),
                              "worker_id": wid, "pid": pid,
                              "job_id": job, "lines": lines})
            if batch:
                try:
                    await self._ctl.call("worker_logs",
                                         {"batch": batch})
                except Exception:
                    # Controller unreachable / handler error: do NOT
                    # advance offsets — the batch re-sends next tick
                    # instead of silently dropping, and ANY exception
                    # must not kill the tailer for the agent's life.
                    continue
                for path, off in advances:
                    offsets[path] = off

    def _worker_by_ref(self, p) -> Optional[WorkerEntry]:
        """Resolve a worker by worker_id hex (prefix ok) or pid."""
        wid, pid = p.get("worker_id"), p.get("pid")
        for w in self.workers.values():
            if pid is not None and w.pid == int(pid):
                return w
            if wid and w.worker_id.hex().startswith(wid):
                return w
        return None

    async def list_worker_logs(self, _p):
        out = []
        known = {w.pid: w for w in self.workers.values()}
        for pid, path in getattr(self, "_worker_log_paths",
                                 {}).items():
            w = known.get(pid)
            try:
                size = os.path.getsize(path)
            except OSError:
                size = -1
            out.append({"pid": pid, "path": path, "size": size,
                        "worker_id": w.worker_id.hex() if w else None,
                        "state": w.state if w else "dead",
                        "job_id": w.job_id if w else None})
        return {"logs": out}

    async def read_worker_log(self, p):
        """Tail a worker's log file — works for DEAD workers too (the
        file outlives the process; ref: dashboard/modules/log/)."""
        path = None
        w = self._worker_by_ref(p)
        if w is not None:
            path = w.log_path
        elif p.get("pid") is not None:
            path = getattr(self, "_worker_log_paths",
                           {}).get(int(p["pid"]))
        if not path:
            return {"ok": False, "error": "unknown worker"}
        max_bytes = int(p.get("max_bytes", 256 * 1024))
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(max(0, size - max_bytes))
                data = f.read(max_bytes)
        except OSError as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True, "path": path,
                "text": data.decode("utf-8", "replace")}

    async def profile_worker(self, p):
        """Sampling-profile a live worker (ref: profile_manager.py:121
        py-spy record — in-process sampler, see util/profiling.py)."""
        w = self._worker_by_ref(p)
        if w is None:
            return {"ok": False, "error": "unknown worker"}
        cli = RpcClient(w.addr, tag="profile")
        try:
            return await cli.call(
                "profile", {"duration_s": p.get("duration_s", 2.0),
                            "hz": p.get("hz", 100.0)},
                )
        finally:
            await cli.close()

    async def stack_worker(self, p):
        w = self._worker_by_ref(p)
        if w is None:
            return {"ok": False, "error": "unknown worker"}
        cli = RpcClient(w.addr, tag="stack")
        try:
            return await cli.call("dump_stack", {})
        finally:
            await cli.close()

    async def node_info(self, _p):
        return {"node_id": self.node_id, "addr": self.server.address,
                "total": dict(self.total.amounts),
                "available": dict(self.available.amounts),
                "workers": len(self.workers),
                "leases": len(self.leases),
                "draining": self._draining,
                "drain_deadline": self._drain_deadline,
                "drain_reason": self._drain_reason}

    async def shutdown(self, _p=None):
        self._shutdown.set()
        if self.is_head and self._store_backend == "pool":
            try:
                self.store.unlink()  # session over: free the tmpfs slab
            except Exception:
                pass
        for w in self.workers.values():
            if w.proc is not None:
                try:
                    w.proc.kill()
                except Exception:
                    pass
            else:
                try:
                    os.kill(w.pid, signal.SIGKILL)
                except Exception:
                    pass
        for proc in self._spawned_procs:
            try:
                proc.kill()
            except Exception:
                pass
        self.directory.clear()
        self.store.close()
        asyncio.get_event_loop().call_soon(
            lambda: spawn_task(self.server.stop()))
        return {"ok": True}

    async def wait_shutdown(self) -> None:
        await self._shutdown.wait()
        await asyncio.sleep(0.1)


def main() -> None:
    import faulthandler

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session", required=True)
    parser.add_argument("--controller", required=True)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    parser.add_argument("--resources", type=str, default="")
    parser.add_argument("--head", action="store_true")
    parser.add_argument("--ready-fd", type=int, default=-1)
    args = parser.parse_args()
    logging.basicConfig(
        level=getattr(logging,
                      os.environ.get("RT_LOG_LEVEL", "INFO").upper(),
                      logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    config = RuntimeConfig.from_env()
    custom = {}
    if args.resources:
        import json

        custom = json.loads(args.resources)

    async def _run():
        agent = NodeAgent(
            config, args.session, args.controller,
            num_cpus=args.num_cpus, num_tpus=args.num_tpus,
            custom_resources=custom, is_head=args.head)
        port = await agent.start(args.port)
        if args.ready_fd >= 0:
            os.write(args.ready_fd,
                     f"{agent.server.address} "
                     f"{agent.node_id.hex()}\n".encode())
            os.close(args.ready_fd)
        else:
            print(f"AGENT_ADDRESS={agent.server.address}", flush=True)
        await agent.wait_shutdown()

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
