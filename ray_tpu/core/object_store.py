"""The per-node shared-memory object plane and per-process memory store.

Role-equivalent to the reference's plasma store + in-process memory store
(ref: src/ray/object_manager/plasma/object_lifecycle_manager.h:101,
src/ray/core_worker/memory_store/memory_store.h:42).  Rebuilt for the TPU
host model: every object is one POSIX shared-memory segment written
zero-copy by the producing worker (pickle-5 out-of-band buffers land
directly in the mapping), readable zero-copy by any process on the node.
The node agent owns the directory + LRU eviction; producers/consumers only
touch the agent for registration and lookup, never for the bytes.

Large-array note: numpy/JAX host arrays dominate object bytes; ``pack``
layout (serialization.py) keeps them as raw contiguous spans so a reader
can reconstruct arrays as views over the mapping without a copy.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Set, Tuple

from .errors import GetTimeoutError
from .ids import ObjectID
from . import serialization

# Suppress resource_tracker interference: segments have explicit lifecycle
# managed by the node agent, not by Python GC in whichever process mapped
# them last.  (The stdlib tracker would unlink segments when *any* process
# that touched them exits.)
from multiprocessing import resource_tracker as _rt


def _untrack(name: str) -> None:
    try:
        _rt.unregister("/" + name, "shared_memory")
    except Exception:
        pass


def _segment_name(session: str, oid: ObjectID) -> str:
    # /dev/shm names are limited to NAME_MAX; 16-byte hex ids fit easily.
    return f"rt_{session}_{oid.hex()}"


@dataclass
class StoredObject:
    """Directory entry for one sealed object in the node store."""

    object_id: ObjectID
    size: int
    create_time: float
    spilled: bool = False   # bytes live on disk, not in shm


class SharedObjectStore:
    """Producer/consumer API over per-object shm segments.

    Any process may create+seal or open segments directly; the node agent's
    ``StoreDirectory`` (below) is the authority on what exists locally and
    enforces capacity.
    """

    def __init__(self, session: str):
        self._session = session
        # Segments this process currently has mapped (for reads), kept so
        # memoryviews returned by get() stay valid.
        self._mapped: Dict[ObjectID, shared_memory.SharedMemory] = {}
        self._lock = threading.Lock()

    # -- producer side ------------------------------------------------------
    def create_and_seal(self, oid: ObjectID, value: Any) -> int:
        """Serialize ``value`` straight into a new segment; returns size."""
        payload, views = serialization.serialize(value)
        return self.seal_parts(oid, payload, views)

    def seal_parts(self, oid: ObjectID, payload: bytes,
                   views) -> int:
        """Write pre-serialized (payload, buffers) into a new segment —
        lets the executor serialize once and choose inline vs plane."""
        size = serialization.packed_size(payload, views)
        seg = self._create_segment(oid, size)
        try:
            buf = seg.buf
            pos = 0
            buf[pos:pos + 4] = len(views).to_bytes(4, "little"); pos += 4
            buf[pos:pos + 8] = len(payload).to_bytes(8, "little"); pos += 8
            buf[pos:pos + len(payload)] = payload; pos += len(payload)
            for v in views:
                n = len(v)
                buf[pos:pos + 8] = n.to_bytes(8, "little"); pos += 8
                if n:
                    buf[pos:pos + n] = v
                pos += n
        finally:
            seg.close()
        return size

    def put_raw(self, oid: ObjectID, data: bytes) -> int:
        """Write pre-packed bytes (object transfer receive path)."""
        seg = self._create_segment(oid, len(data))
        try:
            seg.buf[:len(data)] = data
        finally:
            seg.close()
        return len(data)

    def _create_segment(self, oid: ObjectID,
                        size: int) -> shared_memory.SharedMemory:
        """Create a segment, replacing any stale one with the same name.
        Objects are immutable, but a retry after a mid-write crash (or two
        single-machine 'nodes' sharing /dev/shm) can hit an existing name;
        unlink+recreate keeps old mappings valid for in-flight readers."""
        self.release(oid)  # a re-created name must not serve a stale map
        name = _segment_name(self._session, oid)
        try:
            seg = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        except FileExistsError:
            old = shared_memory.SharedMemory(name=name)
            _untrack(old.name)
            old.close()
            old.unlink()
            seg = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        _untrack(seg.name)
        return seg

    # -- consumer side ------------------------------------------------------
    def _map(self, oid: ObjectID) -> shared_memory.SharedMemory:
        """Map a segment through the per-process cache: repeated reads
        of one object (chunked sends are many slice reads of the same
        segment) reuse a single mapping instead of paying an
        shm_open+mmap per call.  Cached mappings are dropped by
        release()/delete()/close(); an unlinked segment's mapping stays
        valid for in-flight readers (POSIX unlink semantics)."""
        with self._lock:
            seg = self._mapped.get(oid)
            if seg is None:
                seg = shared_memory.SharedMemory(
                    name=_segment_name(self._session, oid))
                _untrack(seg.name)
                self._mapped[oid] = seg
        return seg

    def _read_mapped(self, oid: ObjectID, fn):
        """Run ``fn(seg)`` against the cached mapping, absorbing the
        race where a concurrent delete()/release() closed the cached
        SharedMemory between _map() and the .buf access (ValueError on
        a closed mmap): retry once on a fresh mapping, and surface a
        clean FileNotFoundError — the 'copy vanished' signal readers
        already handle — if the segment is truly gone."""
        try:
            return fn(self._map(oid))
        except ValueError:
            self.release(oid)
            try:
                return fn(self._map(oid))
            except ValueError:
                raise FileNotFoundError(oid.hex()) from None

    def get(self, oid: ObjectID, size: int) -> Any:
        """Map the segment and deserialize (zero-copy for array spans)."""
        return self._read_mapped(
            oid, lambda seg: serialization.unpack(seg.buf[:size]))

    def read_raw(self, oid: ObjectID, size: int) -> bytes:
        """Copy out packed bytes (object transfer send path)."""
        return self._read_mapped(oid, lambda seg: bytes(seg.buf[:size]))

    def read_raw_slice(self, oid: ObjectID, offset: int,
                       length: int) -> bytes:
        """One chunk of the packed bytes (chunked transfer send path,
        ref: push_manager/ObjectBufferPool chunk reads)."""
        return self._read_mapped(
            oid, lambda seg: bytes(seg.buf[offset:offset + length]))


    def contains(self, oid: ObjectID) -> bool:
        try:
            seg = shared_memory.SharedMemory(
                name=_segment_name(self._session, oid))
            _untrack(seg.name)
            seg.close()
            return True
        except FileNotFoundError:
            return False

    @staticmethod
    def _close_or_abandon(seg: shared_memory.SharedMemory) -> None:
        """Close a mapping, or abandon it if zero-copy views still point
        into it: detach the handles so neither close() nor __del__ ever
        touches the exported buffer again.  The mmap object itself stays
        alive exactly as long as the views do (they hold references), so
        the views remain valid and teardown is silent."""
        try:
            seg.close()
        except BufferError:
            seg._buf = None    # noqa: SLF001 — deliberate detach
            seg._mmap = None   # noqa: SLF001
        except Exception:
            pass

    def release(self, oid: ObjectID) -> None:
        with self._lock:
            seg = self._mapped.pop(oid, None)
        if seg is not None:
            self._close_or_abandon(seg)

    def delete(self, oid: ObjectID) -> None:
        self.release(oid)
        try:
            seg = shared_memory.SharedMemory(
                name=_segment_name(self._session, oid))
            _untrack(seg.name)
            seg.close()
            seg.unlink()
        except FileNotFoundError:
            pass

    def close(self) -> None:
        with self._lock:
            for seg in self._mapped.values():
                self._close_or_abandon(seg)
            self._mapped.clear()


class PoolObjectStore:
    """SharedObjectStore-compatible facade over the native C++ pool
    (src/shm_pool.cpp): one shm region per session per host instead of a
    segment per object — object creation is a lock + free-list carve
    with no per-object shm_open/ftruncate syscalls, and reads are
    zero-copy views into the shared mapping (the plasma shape, ref:
    src/ray/object_manager/plasma/).
    """

    # Physical slab = 4x the logical capacity: the directory enforces
    # the logical limit via eviction/spilling, transient read windows
    # may overshoot (same policy as the segment backend), and slab
    # pages are only backed when touched, so slack is nearly free.
    SLACK = 4
    # How long a producer rides seal backpressure before giving up.
    SEAL_PRESSURE_TIMEOUT_S = 60.0

    def __init__(self, session: str, capacity_bytes: int):
        from .._native.shm_pool import ShmPool

        self._session = session
        # Optional hook: called with the needed byte count when the
        # slab is full, so the owner can trigger agent-side eviction.
        self.on_pressure = None
        self._pool = ShmPool(f"/rtpool_{session}",
                             slab_bytes=capacity_bytes * self.SLACK,
                             table_slots=1 << 16)

    @staticmethod
    def _key(oid: ObjectID) -> bytes:
        return oid.binary()

    # -- producer side --------------------------------------------------
    def create_and_seal(self, oid: ObjectID, value: Any) -> int:
        payload, views = serialization.serialize(value)
        return self.seal_parts(oid, payload, views)

    def seal_parts(self, oid: ObjectID, payload: bytes, views) -> int:
        size = serialization.packed_size(payload, views)
        key = self._key(oid)
        # Create backpressure, not hard failure (ref: plasma
        # CreateRequestQueue): when the slab is full — e.g. many
        # producers sealing before the agent's directory has
        # evicted/spilled — ask the agent to make room (on_pressure
        # hook, wired by the runtime to the agent's make_room RPC) and
        # retry with backoff until the deadline.
        deadline = time.monotonic() + self.SEAL_PRESSURE_TIMEOUT_S
        delay = 0.02
        while True:
            buf = self._pool.alloc(key, size)
            if buf is None:
                self._pool.delete(key)  # replace a stale sealed copy
                buf = self._pool.alloc(key, size)
            if buf is not None:
                break
            if time.monotonic() >= deadline:
                raise OSError(f"shm pool full sealing {oid.hex()} "
                              f"({size}B after "
                              f"{self.SEAL_PRESSURE_TIMEOUT_S}s of "
                              "backpressure)")
            if self.on_pressure is not None:
                try:
                    self.on_pressure(size)
                except Exception:
                    pass  # agent unreachable: plain backoff still helps
            time.sleep(delay)
            delay = min(delay * 2, 0.5)
        pos = 0
        buf[pos:pos + 4] = len(views).to_bytes(4, "little"); pos += 4
        buf[pos:pos + 8] = len(payload).to_bytes(8, "little"); pos += 8
        buf[pos:pos + len(payload)] = payload; pos += len(payload)
        for v in views:
            n = len(v)
            buf[pos:pos + 8] = n.to_bytes(8, "little"); pos += 8
            if n:
                buf[pos:pos + n] = v
            pos += n
        if not self._pool.seal(key):
            raise OSError(f"seal failed for {oid.hex()}")
        return size

    def put_raw(self, oid: ObjectID, data) -> int:
        key = self._key(oid)
        if not self._pool.put(key, data):
            self._pool.delete(key)
            if not self._pool.put(key, data):
                raise OSError(f"shm pool full writing {oid.hex()}")
        return len(data)

    # -- consumer side --------------------------------------------------
    # All reads copy out under a cross-process read pin: unlike the
    # segment backend (whose unlinked mappings stay valid for live
    # views), freed pool bytes are RECYCLED, so zero-copy views could
    # silently change under a reader.  Correctness costs one memcpy.
    def _copy(self, oid: ObjectID, offset: int = 0,
              length=None) -> bytes:
        data = self._pool.get_copy(self._key(oid), offset, length)
        if data is None:
            raise FileNotFoundError(oid.hex())
        return data

    def get(self, oid: ObjectID, size: int) -> Any:
        return serialization.unpack(self._copy(oid, 0, size))

    def read_raw(self, oid: ObjectID, size: int) -> bytes:
        return self._copy(oid, 0, size)

    def read_raw_slice(self, oid: ObjectID, offset: int,
                       length: int) -> bytes:
        return self._copy(oid, offset, length)

    def contains(self, oid: ObjectID) -> bool:
        return self._pool.contains(self._key(oid))

    def release(self, oid: ObjectID) -> None:
        pass  # views borrow the session-lifetime mapping

    def delete(self, oid: ObjectID) -> None:
        self._pool.delete(self._key(oid))

    def close(self) -> None:
        self._pool.close()

    def unlink(self) -> None:
        from .._native.shm_pool import ShmPool

        ShmPool.unlink(f"/rtpool_{self._session}")


def create_store(session: str, config) -> Any:
    """Backend factory: ``object_store_backend`` = segments | pool
    (pool requires the native toolchain; falls back to segments)."""
    backend = getattr(config, "object_store_backend", "segments")
    if backend == "pool":
        try:
            return PoolObjectStore(session,
                                   config.object_store_memory_bytes)
        except Exception:
            import logging

            logging.getLogger("ray_tpu.object_store").warning(
                "native pool store unavailable; using segment store",
                exc_info=True)
    return SharedObjectStore(session)


class StoreDirectory:
    """Node-agent-side authority over local objects: registration, LRU
    eviction under capacity pressure, pinning (ref: plasma eviction_policy.h
    + object_lifecycle_manager.h).

    Pin discipline (ref: ObjectLifecycleManager primary-copy pinning):
    the *primary* copy — the one sealed by the producer — is pinned for
    its whole life and released only by an explicit delete (driven by
    distributed ref counting).  Secondary copies (pulled replicas) are
    LRU-evictable, but transient pins taken around reads keep a mid-read
    copy from being unlinked.  Pins are counted, so a read pin on a
    primary copy doesn't unpin its lifetime pin.
    """

    def __init__(self, store: SharedObjectStore, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        self._store = store
        self._capacity = capacity_bytes
        self._spill_dir = spill_dir
        self._entries: "OrderedDict[ObjectID, StoredObject]" = OrderedDict()
        self._pins: Dict[ObjectID, int] = {}       # lifetime (primary)
        self._read_pins: Dict[ObjectID, int] = {}  # transient read guards
        # One spill OR restore in flight per object: the claim holder
        # owns the IO; everyone else waits on the event and re-checks.
        self._io_events: Dict[ObjectID, threading.Event] = {}
        # Agent hook: called (from any thread) with ids whose local copy
        # vanished, so stale locations leave the control plane.
        self.on_evict = None
        self._used = 0
        self._spilled_bytes = 0
        self._spill_count = 0
        self._restore_count = 0
        self._lock = threading.Lock()

    def _spill_path(self, oid: ObjectID) -> str:
        return os.path.join(self._spill_dir, f"{oid.hex()}.bin")

    def register(self, oid: ObjectID, size: int,
                 primary: bool = False) -> List[ObjectID]:
        """Record a sealed object; returns ids evicted to make room.
        ``primary=True`` pins the copy for its lifetime (never evicted;
        only delete() removes it).  Under pressure, unpinned secondary
        copies are LRU-evicted (a copy exists elsewhere); pinned
        primaries are SPILLED to disk instead of running the store over
        capacity (ref: local_object_manager.h:110 SpillObjects)."""
        with self._lock:
            if oid in self._entries:
                if primary:
                    self._pins[oid] = self._pins.get(oid, 0) + 1
                return []
            self._entries[oid] = StoredObject(oid, size, time.time())
            self._entries.move_to_end(oid)
            if primary:
                self._pins[oid] = self._pins.get(oid, 0) + 1
            self._used += size
        return self._shed_pressure(protect=oid)

    def make_room(self, nbytes: int) -> List[ObjectID]:
        """Shed until ``nbytes`` of headroom exists below capacity —
        producer-driven backpressure relief (ref: plasma
        CreateRequestQueue draining the eviction policy): a worker
        whose seal hit a full slab asks its agent to evict/spill NOW
        instead of failing the task."""
        target = max(0, self._capacity - int(nbytes))
        return self._shed_pressure(protect=None, target_used=target)

    def _shed_pressure(self, protect: Optional[ObjectID],
                       target_used: Optional[int] = None
                       ) -> List[ObjectID]:
        """Evict unpinned secondaries, then spill pinned primaries,
        until under capacity (or ``target_used``).  Victims (and their
        per-object IO claim) are taken under the lock; the spill IO
        runs outside it.  Entries with transient read pins or an
        active IO claim are never touched.  Evicted ids also flow to
        ``on_evict`` so the control plane drops their locations."""
        limit = self._capacity if target_used is None else target_used
        evicted: List[ObjectID] = []
        to_spill: List[StoredObject] = []
        with self._lock:
            while self._used > limit:
                victim = None
                for vid, ent in self._entries.items():
                    if vid != protect and not ent.spilled \
                            and self._pins.get(vid, 0) == 0 \
                            and self._read_pins.get(vid, 0) == 0 \
                            and vid not in self._io_events:
                        victim = vid
                        break
                if victim is not None:
                    ent = self._entries.pop(victim)
                    self._used -= ent.size
                    evicted.append(victim)
                    continue
                if self._spill_dir is None:
                    break  # no spill support; run over capacity
                spill_victim = None
                for vid, ent in self._entries.items():
                    if vid != protect and not ent.spilled \
                            and self._read_pins.get(vid, 0) == 0 \
                            and vid not in self._io_events:
                        spill_victim = ent
                        break
                if spill_victim is None:
                    break  # everything else is mid-read; over capacity
                vid = spill_victim.object_id
                spill_victim.spilled = True  # claimed under the lock
                self._io_events[vid] = threading.Event()
                self._used -= spill_victim.size
                self._spilled_bytes += spill_victim.size
                self._spill_count += 1
                to_spill.append(spill_victim)
        for vid in evicted:
            self._store.delete(vid)
        if evicted and self.on_evict is not None:
            try:
                self.on_evict(list(evicted))
            except Exception:
                pass
        for ent in to_spill:
            self._write_spill(ent)
        return evicted

    def _write_spill(self, ent: StoredObject) -> None:
        """Holds the IO claim taken in _shed_pressure.  On any failure
        the accounting reverts and the shm copy stays authoritative —
        a spill must never strand bytes that are still present."""
        oid = ent.object_id
        tmp = self._spill_path(oid) + ".tmp"
        try:
            os.makedirs(self._spill_dir, exist_ok=True)
            data = self._store.read_raw(oid, ent.size)
            with open(tmp, "wb") as f:
                f.write(data)
            with self._lock:
                if oid not in self._entries:
                    # Deleted mid-spill: drop everything.
                    os.remove(tmp)
                    self._store.delete(oid)
                    return
                os.replace(tmp, self._spill_path(oid))
            self._store.delete(oid)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            with self._lock:
                if oid in self._entries and ent.spilled:
                    ent.spilled = False
                    self._used += ent.size
                    self._spilled_bytes -= ent.size
                    self._spill_count -= 1
        finally:
            with self._lock:
                ev = self._io_events.pop(oid, None)
            if ev is not None:
                ev.set()

    def restore(self, oid: ObjectID) -> bool:
        """Bring a spilled object back into shm (ref:
        local_object_manager.h:118 restore path).  Spills and restores
        of one object serialize on the per-object IO claim — exactly
        one owner does IO; everyone else waits and re-checks."""
        while True:
            with self._lock:
                ent = self._entries.get(oid)
                if ent is None:
                    return False
                if not ent.spilled and oid not in self._io_events:
                    return True
                ev = self._io_events.get(oid)
                if ev is None:
                    ev = self._io_events[oid] = threading.Event()
                    break  # we own the restore
            ev.wait(timeout=300)
            # Loop: re-check outcome (restored / deleted / re-spilled).
        try:
            try:
                with open(self._spill_path(oid), "rb") as f:
                    data = f.read()
            except OSError:
                with self._lock:
                    ent = self._entries.get(oid)
                    return ent is not None and not ent.spilled
            try:
                self._store.put_raw(oid, data)
            except OSError:
                # Pool backend can report full (fragmentation / shared
                # slab, transient read-window pins): shed and retry
                # with backoff — a false "lost" here surfaces as
                # ObjectLostError for an object that is safely on disk.
                deadline = time.time() + 30.0
                delay = 0.05
                while True:
                    self._shed_pressure(protect=oid,
                                        target_used=max(
                                            0, self._capacity
                                            - len(data)))
                    try:
                        self._store.put_raw(oid, data)
                        break
                    except OSError:
                        if time.time() >= deadline:
                            return False
                        time.sleep(delay)
                        delay = min(delay * 2, 1.0)
            with self._lock:
                ent = self._entries.get(oid)
                if ent is None:
                    self._store.delete(oid)  # freed while restoring
                    return False
                if ent.spilled:
                    ent.spilled = False
                    self._used += ent.size
                    self._spilled_bytes -= ent.size
                    self._restore_count += 1
            try:
                os.remove(self._spill_path(oid))
            except OSError:
                pass
        finally:
            with self._lock:
                ev2 = self._io_events.pop(oid, None)
            if ev2 is not None:
                ev2.set()
        # Restores grow _used: shed pressure so the store doesn't creep
        # arbitrarily over capacity under a burst of gets.
        self._shed_pressure(protect=oid)
        return True

    def read_spilled(self, oid: ObjectID, offset: int = 0,
                     length: Optional[int] = None) -> Optional[bytes]:
        """Serve spilled bytes straight from disk (remote pulls don't
        need the object back in shm)."""
        try:
            with open(self._spill_path(oid), "rb") as f:
                f.seek(offset)
                return f.read(length if length is not None else -1)
        except OSError:
            return None

    def lookup(self, oid: ObjectID) -> Optional[StoredObject]:
        with self._lock:
            ent = self._entries.get(oid)
            if ent is not None:
                self._entries.move_to_end(oid)
            return ent

    def pin(self, oid: ObjectID) -> None:
        with self._lock:
            self._pins[oid] = self._pins.get(oid, 0) + 1

    def unpin(self, oid: ObjectID) -> None:
        with self._lock:
            n = self._pins.get(oid, 0) - 1
            if n <= 0:
                self._pins.pop(oid, None)
            else:
                self._pins[oid] = n

    def read_pin(self, oid: ObjectID) -> None:
        """Transient guard around a read: blocks eviction AND spilling
        (a lifetime pin only blocks eviction)."""
        with self._lock:
            self._read_pins[oid] = self._read_pins.get(oid, 0) + 1

    def read_unpin(self, oid: ObjectID) -> None:
        with self._lock:
            n = self._read_pins.get(oid, 0) - 1
            if n <= 0:
                self._read_pins.pop(oid, None)
            else:
                self._read_pins[oid] = n

    def delete(self, oid: ObjectID) -> bool:
        with self._lock:
            ent = self._entries.pop(oid, None)
            self._pins.pop(oid, None)
            self._read_pins.pop(oid, None)
            if ent is not None:
                if ent.spilled:
                    self._spilled_bytes -= ent.size
                else:
                    self._used -= ent.size
        if ent is not None:
            if ent.spilled:
                try:
                    os.remove(self._spill_path(oid))
                except OSError:
                    pass
            else:
                self._store.delete(oid)
            return True
        return False

    def stats(self) -> Tuple[int, int, int]:
        with self._lock:
            return len(self._entries), self._used, self._capacity

    def spill_stats(self) -> Dict[str, int]:
        with self._lock:
            return {"spilled_bytes": self._spilled_bytes,
                    "spill_count": self._spill_count,
                    "restore_count": self._restore_count}

    def all_ids(self) -> List[ObjectID]:
        with self._lock:
            return list(self._entries)

    def clear(self) -> None:
        for oid in self.all_ids():
            self.delete(oid)


class _PendingEntry:
    __slots__ = ("event", "value", "has_value")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.has_value = False


class MemoryStore:
    """Per-process store for inlined small values and result descriptors,
    with blocking waits (ref: memory_store.h:42 GetAsync futures)."""

    def __init__(self):
        self._values: Dict[ObjectID, Any] = {}
        self._waiting: Dict[ObjectID, _PendingEntry] = {}
        # Group waiters (wait_for_many): oid -> [{missing:set, event}]
        self._many_waiters: Dict[ObjectID, list] = {}
        self._lock = threading.Lock()

    def put(self, oid: ObjectID, value: Any) -> None:
        fire = None
        with self._lock:
            self._values[oid] = value
            ent = self._waiting.pop(oid, None)
            group = self._many_waiters.pop(oid, None)
            if group:
                for state in group:
                    state["missing"].discard(oid)
                    if not state["missing"]:
                        fire = fire or []
                        fire.append(state["event"])
        if ent is not None:
            ent.value = value
            ent.has_value = True
            ent.event.set()
        for ev in fire or ():
            ev.set()

    def get_nowait(self, oid: ObjectID) -> Tuple[bool, Any]:
        with self._lock:
            if oid in self._values:
                return True, self._values[oid]
        return False, None

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._values

    def wait_for_many(self, oids, timeout: Optional[float]) -> None:
        """Block until EVERY id is present — one shared event set by
        the last arrival instead of a futex wait per ref (a 300-ref
        batched get costs ~2 thread wakeups, not ~300)."""
        import threading as _threading

        missing: set
        with self._lock:
            missing = {o for o in oids if o not in self._values}
            if not missing:
                return
            done = _threading.Event()
            state = {"missing": missing, "event": done}
            for o in missing:
                self._many_waiters.setdefault(o, []).append(state)
        if not done.wait(timeout):
            with self._lock:
                # Unregister or the state dicts leak under every
                # still-missing oid across repeated polling gets.
                for o in list(state["missing"]):
                    group = self._many_waiters.get(o)
                    if group is not None:
                        try:
                            group.remove(state)
                        except ValueError:
                            pass
                        if not group:
                            self._many_waiters.pop(o, None)
            raise GetTimeoutError(
                f"{len(state['missing'])} of {len(list(oids))} objects "
                f"not ready within {timeout}s")

    def wait_for(self, oid: ObjectID, timeout: Optional[float]) -> Any:
        with self._lock:
            if oid in self._values:
                return self._values[oid]
            ent = self._waiting.get(oid)
            if ent is None:
                ent = self._waiting[oid] = _PendingEntry()
        if not ent.event.wait(timeout):
            raise GetTimeoutError(
                f"object {oid.hex()[:16]} not ready within {timeout}s")
        return ent.value

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._values.pop(oid, None)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()
            for ent in self._waiting.values():
                ent.event.set()
            self._waiting.clear()
