"""Placement groups: gang reservation of resource bundles across nodes.

Role-equivalent to the reference's GcsPlacementGroupManager/Scheduler with
its two-phase prepare/commit protocol (ref:
src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h, strategies in
python/ray/util/placement_group.py:145).  TPU-era framing: a bundle is
typically one TPU host's chips; STRICT_SPREAD maps slices across hosts so a
gang-scheduled worker group aligns 1:1 with the jax.distributed world.

Multi-tenant admission: groups carry a ``priority`` (int, default 0) and
an owning ``job`` (the submitted job id).  ONE serialized admission loop
tries pending groups in (priority desc, FIFO) order — a group either
fully admits or fully waits, and two gangs can no longer interleave
partial prepare reservations across nodes (the cross-job deadlock the
per-group schedulers allowed).  While a higher-priority group is blocked
on capacity, strictly-lower-priority groups wait behind it, so freed
capacity always goes to the highest-priority waiter; equal-priority
groups may still backfill smaller holes.  A group blocked past
``preempt_pending_s`` selects victim jobs (strictly lower priority,
newest first) and preempts them through the controller's job-preemption
plane — the drain/checkpoint-on-notice path, not a silent kill.
Per-job quotas gate admission: a group that would run its job over
quota waits (reason ``over_quota``) without blocking other jobs.

Controller-side manager (this file) + client API (placement_api.py).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..util import multitenant
from .ids import NodeID, PlacementGroupID
from .rpc import RpcError, spawn_task

logger = logging.getLogger("ray_tpu.placement")

PENDING = "PENDING"
CREATED = "CREATED"
REMOVED = "REMOVED"
RESCHEDULING = "RESCHEDULING"

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class PGEntry:
    pg_id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = PENDING
    name: str = ""
    # Multi-tenant admission: priority + owning submitted-job id.
    priority: int = 0
    job: str = ""
    # bundle index -> node id (filled at commit)
    placement: Dict[int, NodeID] = field(default_factory=dict)
    create_time: float = field(default_factory=time.time)
    waiters: List[asyncio.Event] = field(default_factory=list)
    # Drain plane: a node hosting one of our bundles is DRAINING —
    # this group will need rescheduling when it dies (surfaced in
    # get()/list so operators see which gangs a drain will move).
    migrate_pending: bool = False
    # Admission bookkeeping: when the group first failed to place and
    # why it is still waiting (no_capacity / over_quota /
    # behind_higher_priority) — the starved-jobs doctor check reads
    # these.
    pending_since: float = 0.0
    pending_reason: str = ""
    preempt_fired_ts: float = 0.0


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _sub(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def _add(avail: Dict[str, float], extra: Dict[str, float]) -> None:
    for k, v in extra.items():
        avail[k] = avail.get(k, 0.0) + v


class PlacementGroupManager:
    def __init__(self, controller):
        self._ctl = controller
        self._groups: Dict[PlacementGroupID, PGEntry] = {}
        self._wakeup = asyncio.Event()
        self._admission_task = None

    # -------------------------------------------------------- admission
    def kick(self) -> None:
        """Wake (or start) the serialized admission loop."""
        self._wakeup.set()
        t = self._admission_task
        if t is None or t.done():
            self._admission_task = spawn_task(self._admission_loop())

    async def _admission_loop(self) -> None:
        """ONE loop admits every pending group, in (priority desc,
        FIFO) order.  Serialization is the anti-deadlock property: at
        most one group is in its prepare/commit window at a time, so
        partial reservations from two racing gangs can never wedge
        each other across nodes."""
        delay = 0.05
        while True:
            self._wakeup.clear()
            pending = sorted(
                (e for e in self._groups.values()
                 if e.state in (PENDING, RESCHEDULING)),
                key=lambda e: multitenant.admission_key(
                    e.priority, e.create_time))
            if not pending:
                if self._wakeup.is_set():
                    continue  # a kick landed after the scan
                return
            progressed = False
            blocked_priority: Optional[int] = None
            now = time.time()
            for entry in pending:
                if entry.state not in (PENDING, RESCHEDULING):
                    continue  # removed/admitted mid-pass
                if blocked_priority is not None and \
                        entry.priority < blocked_priority:
                    # Head-of-line by priority: freed capacity must
                    # reach the blocked higher-priority gang, not be
                    # backfilled by the very job it preempted.
                    if not entry.pending_since:
                        entry.pending_since = now
                    entry.pending_reason = "behind_higher_priority"
                    continue
                if self._over_quota(entry):
                    if not entry.pending_since:
                        entry.pending_since = now
                    entry.pending_reason = "over_quota"
                    continue  # blocked by its own cap; gates nobody
                if await self._try_commit(entry):
                    progressed = True
                else:
                    if not entry.pending_since:
                        entry.pending_since = now
                    entry.pending_reason = "no_capacity"
                    if blocked_priority is None:
                        blocked_priority = entry.priority
                    await self._maybe_preempt(entry, now)
            if progressed:
                delay = 0.05
                continue
            try:
                await asyncio.wait_for(self._wakeup.wait(), delay)
                delay = 0.05
            except asyncio.TimeoutError:
                delay = min(delay * 1.5, 2.0)

    def _over_quota(self, entry: PGEntry) -> bool:
        """Would admitting this group run its job over quota?"""
        if not entry.job:
            return False
        plane = self._ctl.job_plane.get(entry.job)
        quota = plane and plane.get("quota")
        if not quota:
            return False
        need: Dict[str, float] = {}
        for b in entry.bundles:
            _add(need, b)
        used = self._ctl._job_usage(entry.job, exclude_pg=entry.pg_id)
        return multitenant.quota_exceeded(quota, used, need)

    async def _maybe_preempt(self, entry: PGEntry, now: float) -> None:
        """A gang blocked on capacity past the damper selects victim
        jobs — strictly lower priority, newest first — whose eviction
        makes its plan feasible, and drives them into the controller's
        job-preemption plane (notice -> checkpoint-on-notice ->
        announced restart)."""
        cfg = self._ctl.config
        if not cfg.job_preemption_enabled:
            return
        start = entry.pending_since or entry.create_time
        if now - start < cfg.preempt_pending_s:
            return
        if entry.preempt_fired_ts and \
                now - entry.preempt_fired_ts < \
                cfg.preemption_grace_s + 5.0:
            return  # a preemption we triggered is still in flight
        candidates = self._victim_candidates(entry)
        if not candidates:
            return
        victims = multitenant.select_victims(
            candidates,
            feasible_with=lambda credits:
                self._plan(entry, extra=credits) is not None,
            requester_priority=entry.priority)
        if not victims:
            return
        entry.preempt_fired_ts = now
        who = entry.job or entry.pg_id.hex()[:12]
        for job in victims:
            logger.warning("gang %s (job %s, priority %d) preempts "
                           "job %s", entry.pg_id.hex()[:12], who,
                           entry.priority, job)
            await self._ctl.preempt_job({
                "job_id": job,
                "by": entry.job,
                "reason": f"preempted by job {who!r} "
                          f"(priority {entry.priority})"})

    def _victim_candidates(self, entry: PGEntry) -> List[Dict]:
        """Lower-priority jobs holding committed gangs, with the
        per-node credits their eviction would return.  Only job-tagged
        groups are preemptible — anonymous infrastructure groups are
        never victims."""
        alive = {n.node_id for n in self._ctl.nodes.values()
                 if n.alive and not getattr(n, "draining", False)}
        by_job: Dict[str, Dict] = {}
        for e in self._groups.values():
            if e.state != CREATED or not e.job or e.job == entry.job \
                    or e.job in self._ctl.preempting:
                continue
            plane = self._ctl.job_plane.get(e.job, {})
            cand = by_job.setdefault(e.job, {
                "job": e.job,
                "priority": plane.get("priority", e.priority),
                "submit_ts": plane.get("submitted", e.create_time),
                "credits": {}})
            cand["submit_ts"] = min(cand["submit_ts"], e.create_time) \
                if not plane.get("submitted") else cand["submit_ts"]
            for idx, nid in e.placement.items():
                if nid not in alive:
                    continue  # a dead node's capacity is no credit
                multitenant.merge_credits(
                    cand["credits"], {nid: dict(e.bundles[idx])})
        return list(by_job.values())

    async def preempt_job_groups(self, job_id: str,
                                 reason: str = "") -> int:
        """Enforcement teeth: kill the gang workers leased under the
        job's bundles (their deaths surface as the announced failure
        the trainer classifies via the preemption notice), then return
        the bundles so the admission loop's next pass can place the
        preemptor.  Returns the number of groups evicted."""
        evicted = 0
        for entry in [e for e in self._groups.values()
                      if e.job == job_id and e.state != REMOVED]:
            for nid in set(entry.placement.values()):
                cli = await self._ctl._agent(nid)
                if cli is None:
                    continue
                try:
                    await cli.call("preempt_pg_leases", {
                        "pg_id": entry.pg_id, "reason": reason})
                except RpcError:
                    pass  # node already dying takes its workers along
            await self.remove({"pg_id": entry.pg_id})
            evicted += 1
        if evicted:
            self.kick()
        return evicted

    # ------------------------------------------------------------- placement
    def _plan(self, entry: PGEntry,
              extra: Optional[Dict[Any, Dict[str, float]]] = None
              ) -> Optional[Dict[int, NodeID]]:
        """Bin-pack bundles onto alive nodes per strategy (ref:
        BundleSchedulingPolicy in src/ray/raylet/scheduling/policy/).
        ``extra`` credits hypothetical per-node availability — the
        victim-selection simulation asks "would this plan work if that
        job's bundles came back?"."""
        nodes = [n for n in self._ctl.nodes.values()
                 if n.alive and not getattr(n, "draining", False)]
        if not nodes:
            return None
        avail = {n.node_id: dict(n.resources_available) for n in nodes}
        for nid, credit in (extra or {}).items():
            if nid in avail:
                _add(avail[nid], credit)
        plan: Dict[int, NodeID] = {}
        strategy = entry.strategy
        order = sorted(range(len(entry.bundles)),
                       key=lambda i: -sum(entry.bundles[i].values()))
        if strategy in ("PACK", "STRICT_PACK"):
            # Try to place everything on a single node first.
            for n in nodes:
                trial = dict(avail[n.node_id])
                ok = True
                for i in order:
                    if not _fits(trial, entry.bundles[i]):
                        ok = False
                        break
                    _sub(trial, entry.bundles[i])
                if ok:
                    return {i: n.node_id for i in order}
            if strategy == "STRICT_PACK":
                return None
            # Soft PACK: greedy fill, spill to other nodes.
            for i in order:
                placed = False
                for n in nodes:
                    if _fits(avail[n.node_id], entry.bundles[i]):
                        _sub(avail[n.node_id], entry.bundles[i])
                        plan[i] = n.node_id
                        placed = True
                        break
                if not placed:
                    return None
            return plan
        # SPREAD family: round-robin across distinct nodes.
        used_nodes: List[NodeID] = []
        for i in order:
            candidates = sorted(
                nodes, key=lambda n: (n.node_id in used_nodes,
                                      -sum(avail[n.node_id].values())))
            placed = False
            for n in candidates:
                if strategy == "STRICT_SPREAD" and n.node_id in used_nodes:
                    continue
                if _fits(avail[n.node_id], entry.bundles[i]):
                    _sub(avail[n.node_id], entry.bundles[i])
                    plan[i] = n.node_id
                    used_nodes.append(n.node_id)
                    placed = True
                    break
            if not placed:
                return None
        return plan

    async def _try_commit(self, entry: PGEntry) -> bool:
        plan = self._plan(entry)
        if plan is None:
            return False
        # Phase 1: prepare — reserve on every node, all-or-nothing.
        prepared: List[int] = []
        ok = True
        for idx, node_id in plan.items():
            cli = await self._ctl._agent(node_id)
            if cli is None:
                ok = False
                break
            try:
                r = await cli.call("prepare_bundle", {
                    "pg_id": entry.pg_id, "bundle_index": idx,
                    "resources": entry.bundles[idx]})
            except RpcError:
                ok = False
                break
            if not r.get("ok"):
                ok = False
                break
            prepared.append(idx)
        # The prepare RPCs awaited: a remove()/preemption may have
        # landed mid-window — committing now would resurrect a dead
        # group with reserved-but-unreleasable bundles.
        if entry.state == REMOVED:
            ok = False
        if not ok:
            for idx in prepared:
                cli = await self._ctl._agent(plan[idx])
                if cli is not None:
                    try:
                        await cli.call("return_bundle", {
                            "pg_id": entry.pg_id, "bundle_index": idx})
                    except RpcError:
                        pass
            return False
        # Phase 2: commit.
        for idx, node_id in plan.items():
            cli = await self._ctl._agent(node_id)
            if cli is not None:
                try:
                    await cli.call("commit_bundle", {
                        "pg_id": entry.pg_id, "bundle_index": idx})
                except RpcError:
                    pass
        entry.placement = plan
        entry.state = CREATED
        entry.pending_since = 0.0
        entry.pending_reason = ""
        entry.preempt_fired_ts = 0.0
        for ev in entry.waiters:
            ev.set()
        entry.waiters.clear()
        self._ctl._publish("placement_group",
                           {"pg_id": entry.pg_id, "state": CREATED})
        return True

    # ----------------------------------------------------------------- RPCs
    async def create(self, p):
        strategy = p.get("strategy", "PACK")
        if strategy not in STRATEGIES:
            return {"ok": False, "error": f"unknown strategy {strategy!r}"}
        entry = PGEntry(pg_id=p["pg_id"], bundles=p["bundles"],
                        strategy=strategy, name=p.get("name", ""),
                        priority=int(p.get("priority") or 0),
                        job=p.get("job") or "")
        self._groups[entry.pg_id] = entry
        self.kick()
        return {"ok": True}

    async def remove(self, p):
        entry = self._groups.get(p["pg_id"])
        if entry is None:
            return {"ok": True}
        entry.state = REMOVED
        for idx, node_id in entry.placement.items():
            cli = await self._ctl._agent(node_id)
            if cli is not None:
                try:
                    await cli.call("return_bundle", {
                        "pg_id": entry.pg_id, "bundle_index": idx})
                except RpcError:
                    pass
        entry.placement.clear()
        for ev in entry.waiters:
            ev.set()
        self._ctl._publish("placement_group",
                           {"pg_id": entry.pg_id, "state": REMOVED})
        # Returned bundles are capacity for whoever is next in line.
        self.kick()
        return {"ok": True}

    def get(self, p):
        entry = self._groups.get(p["pg_id"])
        if entry is None:
            return None
        placement = {
            idx: {"node_id": nid,
                  "agent_addr": self._ctl.nodes[nid].agent_addr
                  if nid in self._ctl.nodes else ""}
            for idx, nid in entry.placement.items()
        }
        return {"pg_id": entry.pg_id, "state": entry.state,
                "bundles": entry.bundles, "strategy": entry.strategy,
                "placement": placement, "name": entry.name,
                "priority": entry.priority, "job": entry.job,
                "create_time": entry.create_time,
                "pending_since": entry.pending_since,
                "pending_reason": entry.pending_reason,
                "migrate_pending": entry.migrate_pending}

    def list_all(self, _p):
        return [self.get({"pg_id": pid}) for pid in self._groups]

    def on_node_draining(self, node_id: NodeID) -> None:
        """Mark groups with bundles on a draining node for migration.
        Rescheduling itself waits for the node's death — bundles must
        not be yanked from under the live gang that is spending the
        grace window on a checkpoint-on-notice."""
        for entry in self._groups.values():
            if entry.state == CREATED and \
                    node_id in entry.placement.values() and \
                    not entry.migrate_pending:
                entry.migrate_pending = True
                self._ctl._publish("placement_group", {
                    "pg_id": entry.pg_id, "state": entry.state,
                    "migrate_pending": True})

    async def on_node_dead(self, node_id: NodeID) -> None:
        for entry in self._groups.values():
            if entry.state == CREATED and node_id in entry.placement.values():
                entry.state = RESCHEDULING
                # Return the bundles still held by SURVIVING nodes before
                # re-planning, or their reservations leak forever.
                for idx, nid in list(entry.placement.items()):
                    if nid == node_id:
                        continue
                    cli = await self._ctl._agent(nid)
                    if cli is not None:
                        try:
                            await cli.call("return_bundle", {
                                "pg_id": entry.pg_id, "bundle_index": idx})
                        except RpcError:
                            pass
                entry.placement = {}
                entry.migrate_pending = False  # migration underway
                entry.pending_since = 0.0
                entry.pending_reason = ""
                self._ctl._publish("placement_group",
                                   {"pg_id": entry.pg_id,
                                    "state": RESCHEDULING})
        self.kick()
