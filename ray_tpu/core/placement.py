"""Placement groups: gang reservation of resource bundles across nodes.

Role-equivalent to the reference's GcsPlacementGroupManager/Scheduler with
its two-phase prepare/commit protocol (ref:
src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h, strategies in
python/ray/util/placement_group.py:145).  TPU-era framing: a bundle is
typically one TPU host's chips; STRICT_SPREAD maps slices across hosts so a
gang-scheduled worker group aligns 1:1 with the jax.distributed world.

Controller-side manager (this file) + client API (placement_api.py).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .ids import NodeID, PlacementGroupID
from .rpc import RpcError, spawn_task

logger = logging.getLogger("ray_tpu.placement")

PENDING = "PENDING"
CREATED = "CREATED"
REMOVED = "REMOVED"
RESCHEDULING = "RESCHEDULING"

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class PGEntry:
    pg_id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = PENDING
    name: str = ""
    # bundle index -> node id (filled at commit)
    placement: Dict[int, NodeID] = field(default_factory=dict)
    create_time: float = field(default_factory=time.time)
    waiters: List[asyncio.Event] = field(default_factory=list)
    # Drain plane: a node hosting one of our bundles is DRAINING —
    # this group will need rescheduling when it dies (surfaced in
    # get()/list so operators see which gangs a drain will move).
    migrate_pending: bool = False


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _sub(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class PlacementGroupManager:
    def __init__(self, controller):
        self._ctl = controller
        self._groups: Dict[PlacementGroupID, PGEntry] = {}

    # ------------------------------------------------------------- placement
    def _plan(self, entry: PGEntry) -> Optional[Dict[int, NodeID]]:
        """Bin-pack bundles onto alive nodes per strategy (ref:
        BundleSchedulingPolicy in src/ray/raylet/scheduling/policy/)."""
        nodes = [n for n in self._ctl.nodes.values()
                 if n.alive and not getattr(n, "draining", False)]
        if not nodes:
            return None
        avail = {n.node_id: dict(n.resources_available) for n in nodes}
        plan: Dict[int, NodeID] = {}
        strategy = entry.strategy
        order = sorted(range(len(entry.bundles)),
                       key=lambda i: -sum(entry.bundles[i].values()))
        if strategy in ("PACK", "STRICT_PACK"):
            # Try to place everything on a single node first.
            for n in nodes:
                trial = dict(avail[n.node_id])
                ok = True
                for i in order:
                    if not _fits(trial, entry.bundles[i]):
                        ok = False
                        break
                    _sub(trial, entry.bundles[i])
                if ok:
                    return {i: n.node_id for i in order}
            if strategy == "STRICT_PACK":
                return None
            # Soft PACK: greedy fill, spill to other nodes.
            for i in order:
                placed = False
                for n in nodes:
                    if _fits(avail[n.node_id], entry.bundles[i]):
                        _sub(avail[n.node_id], entry.bundles[i])
                        plan[i] = n.node_id
                        placed = True
                        break
                if not placed:
                    return None
            return plan
        # SPREAD family: round-robin across distinct nodes.
        used_nodes: List[NodeID] = []
        for i in order:
            candidates = sorted(
                nodes, key=lambda n: (n.node_id in used_nodes,
                                      -sum(avail[n.node_id].values())))
            placed = False
            for n in candidates:
                if strategy == "STRICT_SPREAD" and n.node_id in used_nodes:
                    continue
                if _fits(avail[n.node_id], entry.bundles[i]):
                    _sub(avail[n.node_id], entry.bundles[i])
                    plan[i] = n.node_id
                    used_nodes.append(n.node_id)
                    placed = True
                    break
            if not placed:
                return None
        return plan

    async def _try_commit(self, entry: PGEntry) -> bool:
        plan = self._plan(entry)
        if plan is None:
            return False
        # Phase 1: prepare — reserve on every node, all-or-nothing.
        prepared: List[int] = []
        ok = True
        for idx, node_id in plan.items():
            cli = await self._ctl._agent(node_id)
            if cli is None:
                ok = False
                break
            try:
                r = await cli.call("prepare_bundle", {
                    "pg_id": entry.pg_id, "bundle_index": idx,
                    "resources": entry.bundles[idx]})
            except RpcError:
                ok = False
                break
            if not r.get("ok"):
                ok = False
                break
            prepared.append(idx)
        if not ok:
            for idx in prepared:
                cli = await self._ctl._agent(plan[idx])
                if cli is not None:
                    try:
                        await cli.call("return_bundle", {
                            "pg_id": entry.pg_id, "bundle_index": idx})
                    except RpcError:
                        pass
            return False
        # Phase 2: commit.
        for idx, node_id in plan.items():
            cli = await self._ctl._agent(node_id)
            if cli is not None:
                try:
                    await cli.call("commit_bundle", {
                        "pg_id": entry.pg_id, "bundle_index": idx})
                except RpcError:
                    pass
        entry.placement = plan
        entry.state = CREATED
        for ev in entry.waiters:
            ev.set()
        entry.waiters.clear()
        self._ctl._publish("placement_group",
                           {"pg_id": entry.pg_id, "state": CREATED})
        return True

    async def _schedule_loop(self, entry: PGEntry) -> None:
        delay = 0.05
        while entry.state in (PENDING, RESCHEDULING):
            if await self._try_commit(entry):
                return
            await asyncio.sleep(delay)
            delay = min(delay * 1.5, 2.0)

    # ----------------------------------------------------------------- RPCs
    async def create(self, p):
        strategy = p.get("strategy", "PACK")
        if strategy not in STRATEGIES:
            return {"ok": False, "error": f"unknown strategy {strategy!r}"}
        entry = PGEntry(pg_id=p["pg_id"], bundles=p["bundles"],
                        strategy=strategy, name=p.get("name", ""))
        self._groups[entry.pg_id] = entry
        spawn_task(self._schedule_loop(entry))
        return {"ok": True}

    async def remove(self, p):
        entry = self._groups.get(p["pg_id"])
        if entry is None:
            return {"ok": True}
        entry.state = REMOVED
        for idx, node_id in entry.placement.items():
            cli = await self._ctl._agent(node_id)
            if cli is not None:
                try:
                    await cli.call("return_bundle", {
                        "pg_id": entry.pg_id, "bundle_index": idx})
                except RpcError:
                    pass
        entry.placement.clear()
        for ev in entry.waiters:
            ev.set()
        self._ctl._publish("placement_group",
                           {"pg_id": entry.pg_id, "state": REMOVED})
        return {"ok": True}

    def get(self, p):
        entry = self._groups.get(p["pg_id"])
        if entry is None:
            return None
        placement = {
            idx: {"node_id": nid,
                  "agent_addr": self._ctl.nodes[nid].agent_addr
                  if nid in self._ctl.nodes else ""}
            for idx, nid in entry.placement.items()
        }
        return {"pg_id": entry.pg_id, "state": entry.state,
                "bundles": entry.bundles, "strategy": entry.strategy,
                "placement": placement, "name": entry.name,
                "migrate_pending": entry.migrate_pending}

    def list_all(self, _p):
        return [self.get({"pg_id": pid}) for pid in self._groups]

    def on_node_draining(self, node_id: NodeID) -> None:
        """Mark groups with bundles on a draining node for migration.
        Rescheduling itself waits for the node's death — bundles must
        not be yanked from under the live gang that is spending the
        grace window on a checkpoint-on-notice."""
        for entry in self._groups.values():
            if entry.state == CREATED and \
                    node_id in entry.placement.values() and \
                    not entry.migrate_pending:
                entry.migrate_pending = True
                self._ctl._publish("placement_group", {
                    "pg_id": entry.pg_id, "state": entry.state,
                    "migrate_pending": True})

    async def on_node_dead(self, node_id: NodeID) -> None:
        for entry in self._groups.values():
            if entry.state == CREATED and node_id in entry.placement.values():
                entry.state = RESCHEDULING
                # Return the bundles still held by SURVIVING nodes before
                # re-planning, or their reservations leak forever.
                for idx, nid in list(entry.placement.items()):
                    if nid == node_id:
                        continue
                    cli = await self._ctl._agent(nid)
                    if cli is not None:
                        try:
                            await cli.call("return_bundle", {
                                "pg_id": entry.pg_id, "bundle_index": idx})
                        except RpcError:
                            pass
                entry.placement = {}
                entry.migrate_pending = False  # migration underway
                self._ctl._publish("placement_group",
                                   {"pg_id": entry.pg_id,
                                    "state": RESCHEDULING})
                spawn_task(self._schedule_loop(entry))
