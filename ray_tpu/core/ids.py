"""Deterministic binary identifiers for jobs, tasks, actors, objects, and nodes.

Mirrors the derivation scheme of the reference runtime (ref:
src/ray/common/id.h) without copying its layout: every ID is a fixed-size
byte string; TaskIDs are derived from (parent task, submission counter) and
ObjectIDs from (task, return/put index), so any process can compute the IDs
of a task's returns without coordination.  TPU-era note: IDs are pure host
metadata and never enter compiled XLA programs.
"""

from __future__ import annotations

import hashlib
import os
import threading

_UNIQUE_BYTES = 16


def _hash(*parts: bytes) -> bytes:
    h = hashlib.sha256()
    for p in parts:
        h.update(p)
    return h.digest()[:_UNIQUE_BYTES]


class BaseID:
    """A fixed-width binary identifier with hex repr and value semantics."""

    SIZE = _UNIQUE_BYTES
    __slots__ = ("_bytes",)

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(cls.SIZE, "big"))

    def to_int(self) -> int:
        return int.from_bytes(self._bytes, "big")


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID, parent_task_id: "TaskID", counter: int) -> "ActorID":
        return cls(
            _hash(b"actor", job_id.binary(), parent_task_id.binary(),
                  counter.to_bytes(8, "big"))[: cls.SIZE]
        )


class PlacementGroupID(BaseID):
    SIZE = 12


class TaskID(BaseID):
    SIZE = 14

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(_hash(b"driver", job_id.binary())[: cls.SIZE])

    @classmethod
    def of(cls, job_id: JobID, parent_task_id: "TaskID", counter: int) -> "TaskID":
        return cls(
            _hash(b"task", job_id.binary(), parent_task_id.binary(),
                  counter.to_bytes(8, "big"))[: cls.SIZE]
        )

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(_hash(b"actor_creation", actor_id.binary())[: cls.SIZE])


class ObjectID(BaseID):
    """ObjectID = hash(task_id, index).  index >= 1 for returns; put objects
    use a separate namespace so puts and returns never collide."""

    SIZE = 16

    @classmethod
    def for_task_return(cls, task_id: TaskID, return_index: int) -> "ObjectID":
        return cls(_hash(b"return", task_id.binary(), return_index.to_bytes(4, "big")))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(_hash(b"put", task_id.binary(), put_index.to_bytes(4, "big")))


ObjectRefID = ObjectID  # alias used by the public ObjectRef type


class _Counter:
    """Thread-safe monotonically increasing counter (per task/actor context)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
