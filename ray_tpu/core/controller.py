"""The controller process — cluster metadata authority.

Role-equivalent to the reference's GCS server (ref:
src/ray/gcs/gcs_server/gcs_server.h:89 and its manager classes): node
membership + health checks, actor directory with restart orchestration,
named actors, an object location directory, a KV store (collective
rendezvous, function table), cursor-based pubsub, and job registration.
Single asyncio process; all state lives on the loop thread so no locks.

Deviation from the reference, on purpose: the object *location* directory
is centralized here rather than owner-distributed — at TPU-host
granularity the directory is small (hosts, not chips, hold objects) and a
single authority removes the owner-failure protocol; lineage-based
reconstruction still lives with the owning worker (see
cluster_runtime.py:_reconstruct_object and its retry bookkeeping).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from .config import RuntimeConfig
from .ids import ActorID, JobID, NodeID, ObjectID
from .rpc import RpcClient, RpcError, RpcServer, spawn_task

logger = logging.getLogger("ray_tpu.controller")

# Actor lifecycle states (ref: gcs.proto ActorTableData.ActorState).
PENDING = "PENDING"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"

# Task-state lifecycle tiers for headline-state resolution: terminal
# execution states outrank RUNNING, which outranks every owner-side
# scheduling state (QUEUED/LEASE_REQUESTED/PIPELINED/GRANTED/REQUEUED,
# all tier 1).  Owner and worker clocks are different hosts, so tiers
# — not timestamps — decide across the two planes.
_STATE_TIER = {"FINISHED": 3, "FAILED": 3, "RUNNING": 2}


@dataclass
class NodeEntry:
    node_id: NodeID
    agent_addr: str
    resources_total: Dict[str, float]
    resources_available: Dict[str, float]
    last_heartbeat: float
    alive: bool = True
    labels: Dict[str, str] = field(default_factory=dict)
    is_head: bool = False
    idle_s: float = 0.0                 # autoscaler: node idle duration
    pending_demands: List = field(default_factory=list)
    # Drain plane: set by node_draining / drain_node and refreshed by
    # the agent's heartbeat; drives lease-avoidance (resource_view),
    # the autoscaler's proactive replacement, and doctor's stale-drain
    # check.
    draining: bool = False
    drain_deadline: float = 0.0
    drain_reason: str = ""
    drain_replace: bool = True
    # Prestart-pool occupancy mirrored from the agent heartbeat
    # ({idle, target, adoptions, cold_spawns}) for `rt status` and
    # the dashboard node table.
    worker_pool: Dict = field(default_factory=dict)


@dataclass
class ActorEntry:
    actor_id: ActorID
    state: str
    class_name: str
    method_names: List[str]
    node_id: Optional[NodeID] = None
    worker_addr: str = ""
    name: str = ""
    namespace: str = ""
    restarts_remaining: int = 0
    creation_spec: Any = None          # pickled TaskSpec replayed on restart
    owner_addr: str = ""
    death_reason: str = ""
    detached: bool = False
    max_concurrency: int = 1


class Controller:
    def __init__(self, config: RuntimeConfig, session: str):
        self.config = config
        self.session = session
        self.server = RpcServer()
        self.nodes: Dict[NodeID, NodeEntry] = {}
        self.actors: Dict[ActorID, ActorEntry] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.kv: Dict[str, bytes] = {}
        self.kv_list_counts: Dict[str, int] = {}  # kv_append item counts
        self.object_dir: Dict[ObjectID, Dict] = {}  # oid -> {nodes:set,size}
        self.events: Dict[str, List[Tuple[int, Any]]] = {}
        self.events_trimmed_to: Dict[str, int] = {}  # ch -> last trimmed seq
        self.event_seq = 0
        self.event_waiters: List[asyncio.Event] = []
        self.jobs: Dict[int, Dict] = {}
        self.job_counter = 1
        # Multi-tenant job plane: per-submitted-job metadata keyed by
        # the STRING submission id (the `job-...` id the supervisor
        # registers) — priority, optional resource quota, submit time.
        # Distinct from self.jobs, which tracks internal driver
        # registrations; the two link through the driver's RT_JOB_ID
        # (register_job's "tenant" field).
        self.job_plane: Dict[str, Dict] = {}
        # Active preemption notices: job_id -> {deadline, reason, by}.
        # The victim's trainer polls job_preemption_state on its drain
        # cadence; at the deadline _job_preemption_loop enforces by
        # evicting the job's placement groups.
        self.preempting: Dict[str, Dict] = {}
        # Agent-reported plain-lease usage per node: node_hex ->
        # {internal_job_hex: {resource: amount}} (PG-bound leases are
        # excluded — bundle reservations are counted controller-side).
        self._job_usage_by_node: Dict[str, Dict[str, Dict]] = {}
        # Task-event sink (ref: gcs_task_manager.h:86 GcsTaskManager):
        # bounded per-task records for the state API + Chrome-trace
        # timeline export; oldest finished records are dropped first.
        from collections import OrderedDict

        self.task_records: "OrderedDict[str, Dict]" = OrderedDict()
        self.task_events_dropped = 0
        # Hot-path phase sink: sampled task stamp records (sliced into
        # named lifecycle phases by the owner) arriving piggybacked on
        # task_events flushes; `rt hotpath` reads its snapshot.
        from ray_tpu.util.hotpath import Sink as _HotpathSink

        self.hotpath_sink = _HotpathSink()
        # Cluster metrics: latest snapshot per reporting source (ref:
        # metrics agent / opencensus exporter, metric_defs.cc).
        self.metrics_sources: Dict[str, Any] = {}
        # Flight-recorder dumps forwarded by node agents when a worker
        # dies (bounded; newest wins per source).
        self.flight_dumps: "OrderedDict[str, Dict]" = OrderedDict()
        # Cross-process span sink (collectives, train-step phases,
        # serve requests, explicit tracing spans) drained from every
        # worker/driver ring on the heartbeat cadence; merged with
        # task_records by the cluster timeline export.
        from collections import deque as _deque

        self.span_records: "_deque[Dict]" = _deque(
            maxlen=self.config.task_event_buffer_size)
        self.spans_received = 0
        # Slowest-request exemplars per window, fed from finished
        # ingress spans as they arrive — `rt trace` (no argument) and
        # the doctor's find_slow_requests read this instead of
        # re-scanning the whole span sink.
        from ray_tpu.util.reqtrace import ExemplarRing

        self.request_exemplar_ring = ExemplarRing(
            capacity=int(os.environ.get("RT_TRACE_EXEMPLARS", "32")),
            window_s=float(os.environ.get(
                "RT_TRACE_EXEMPLAR_WINDOW_S", "600")))
        # On-demand profiler artifacts (e.g. jax.profiler trace dirs)
        # reported by node agents after an `rt profile --jax` capture.
        self.profile_artifacts: "_deque[Dict]" = _deque(maxlen=64)
        # Gang-watchdog input: per-source inflight collective-entry
        # stamps, REPLACED on every report (an exited op vanishes on
        # the reporter's next tick; a hung one keeps refreshing).
        self.collective_reports: Dict[str, Dict] = {}
        # Autoscaler decision ring: one bounded record per reconcile
        # tick that acted or found unsatisfiable demand — the "why
        # didn't it scale" answer (round-5 demand-blindness weakness).
        self.autoscaler_decisions: "_deque[Dict]" = _deque(maxlen=128)
        self._agent_clients: Dict[NodeID, RpcClient] = {}
        self._placement = None  # PlacementGroupManager, attached in setup
        self._shutdown = asyncio.Event()
        for name in [
            "register_node", "heartbeat", "list_nodes", "resource_view",
            "register_actor", "register_actors", "actor_started",
            "actors_started", "actor_died", "get_actor",
            "lookup_named_actor", "kill_actor", "worker_exited",
            "kv_put", "kv_get", "kv_del", "kv_keys", "kv_append", "kv_list",
            "publish_locations", "remove_locations", "update_locations",
            "locate_object", "locate_objects",
            "free_object", "owner_release", "add_borrower",
            "remove_borrower", "link_induced_borrows",
            "poll_events", "register_job", "finish_job",
            "create_placement_group", "remove_placement_group",
            "get_placement_group", "list_placement_groups",
            "list_actors", "cluster_shutdown", "ping", "drain_node",
            "node_draining",
            "task_events", "hotpath", "list_tasks", "get_task",
            "list_objects",
            "list_jobs", "report_metrics", "metrics_text",
            "metrics_history", "get_load_metrics", "worker_logs",
            "telemetry", "report_flight_dump",
            "report_spans", "list_spans", "report_profile",
            "request_exemplars",
            "explain_task", "collective_entries",
            "report_autoscaler_decision", "doctor_feed",
            "job_register", "jobs_overview", "preempt_job",
            "job_preemption_state",
        ]:
            self.server.register(name, getattr(self, name))

    # ------------------------------------------------------------------ util
    def _publish(self, channel: str, data: Any) -> None:
        self._mark_dirty()  # every table mutation publishes
        self.event_seq += 1
        self.events.setdefault(channel, []).append((self.event_seq, data))
        log = self.events[channel]
        if len(log) > self.config.task_event_buffer_size:
            n = len(log) // 2
            # Remember the highest trimmed seq so slow subscribers whose
            # cursor predates it get an explicit cursor_expired signal
            # (they must resync) instead of silently skipping events.
            self.events_trimmed_to[channel] = log[n - 1][0]
            del log[:n]
        for ev in self.event_waiters:
            ev.set()

    async def _agent(self, node_id: NodeID) -> Optional[RpcClient]:
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return None
        cli = self._agent_clients.get(node_id)
        if cli is None or not cli.connected:
            # Short dial window: these are same-DC control-plane dials
            # to agents that already registered.  The default 30s
            # retry loop means every RPC aimed at a just-died (but not
            # yet marked dead) node — kill_actor during a gang
            # teardown, drain_node during a preemption wave — wedges
            # its caller for half a minute.
            cli = RpcClient(node.agent_addr,
                            tag=f"controller->{node_id.hex()[:8]}",
                            connect_timeout=3.0)
            try:
                await cli.connect()
            except RpcError:
                return None
            self._agent_clients[node_id] = cli
        return cli

    # ----------------------------------------------------------------- nodes
    async def register_node(self, p):
        node_id = p["node_id"]
        entry = NodeEntry(
            node_id=node_id, agent_addr=p["agent_addr"],
            resources_total=p["resources"],
            resources_available=dict(p["resources"]),
            last_heartbeat=time.time(), labels=p.get("labels", {}),
            is_head=p.get("is_head", False))
        self.nodes[node_id] = entry
        self._publish("node", {"node_id": node_id, "state": "ALIVE",
                               "agent_addr": entry.agent_addr})
        logger.info("node %s registered (%s)", node_id.hex()[:8],
                    p["agent_addr"])
        return {"ok": True, "session": self.session}

    async def heartbeat(self, p):
        node = self.nodes.get(p["node_id"])
        if node is None:
            return {"ok": False, "reregister": True}
        if not node.alive:
            # The health loop declared this node dead (missed
            # heartbeats — e.g. its event loop starved under a worker
            # fork storm), but the agent is clearly still with us.
            # Without this, a transiently-stalled agent is a PERMANENT
            # zombie: it keeps heartbeating into a row nothing ever
            # resurrects, invisible to scheduling forever.  Route it
            # through the same re-register protocol a restarted
            # controller uses — register_node rebuilds the row alive
            # and the agent republishes its object locations.
            return {"ok": False, "reregister": True}
        node.last_heartbeat = time.time()
        node.resources_available = p.get("available", node.resources_available)
        if "total" in p:
            node.resources_total = p["total"]
        node.idle_s = p.get("idle_s", 0.0)
        node.pending_demands = p.get("pending_demands", [])
        if "worker_pool" in p:
            node.worker_pool = p["worker_pool"] or {}
        if p.get("draining"):
            # The agent's own view is authoritative once it drains;
            # a heartbeat that predates a drain_node RPC must NOT
            # clear controller-marked drain state (drains are one-way
            # until the node dies).  The deadline arrives as REMAINING
            # seconds and is re-anchored to the controller clock here
            # — the stale-drain check compares against this clock, and
            # agent wall time can be arbitrarily skewed.
            node.draining = True
            remaining = p.get("drain_remaining_s")
            if remaining is not None:
                node.drain_deadline = time.time() + float(remaining)
            else:
                node.drain_deadline = p.get("drain_deadline", 0.0)
            node.drain_reason = p.get("drain_reason", "")
            node.drain_replace = p.get("drain_replace", True)
        if "job_usage" in p:
            self._job_usage_by_node[node.node_id.hex()] = \
                p["job_usage"] or {}
        out = {"ok": True}
        view = self._job_quota_view()
        if view:
            # Quota/priority view for lease-grant-time enforcement at
            # the agent: {internal_job_hex: {job, priority, quota,
            # used}}.  Eventually consistent within a heartbeat period
            # — the agent overlays its own since-last-report grants.
            out["jobs"] = view
        return out

    async def get_load_metrics(self, _p):
        """Autoscaler input: per-node utilization + unsatisfied demand
        (ref: autoscaler/_private/load_metrics.py fed from GCS)."""
        nodes = {}
        demands = []
        for n in self.nodes.values():
            if not n.alive:
                continue
            nodes[n.node_id.hex()] = {
                "available": dict(n.resources_available),
                "total": dict(n.resources_total),
                "idle_s": getattr(n, "idle_s", 0.0),
                "is_head": n.is_head,
                "agent_addr": n.agent_addr,
                "draining": n.draining,
                "drain_deadline": n.drain_deadline,
            }
            demands.extend(getattr(n, "pending_demands", []))
            if n.draining and n.drain_replace:
                # Proactive replacement: a draining node's capacity is
                # leaving the cluster — advertise its full shape as
                # demand NOW so the autoscaler starts a replacement
                # during the grace window instead of after the death
                # (idle-timeout drains pass replace=False; replacing a
                # node the scaler itself is reaping would thrash).
                demands.append(dict(n.resources_total))
        pg_demands = []
        if self._placement is not None:
            for entry in self._placement._groups.values():
                if entry.state in ("PENDING", "RESCHEDULING"):
                    pg_demands.append({"bundles": list(entry.bundles),
                                       "strategy": entry.strategy,
                                       "priority": getattr(entry,
                                                           "priority", 0),
                                       "job": getattr(entry, "job", "")})
        return {"nodes": nodes, "pending_demands": demands,
                "pending_placement_groups": pg_demands}

    async def list_nodes(self, _p):
        return [
            {"node_id": n.node_id, "agent_addr": n.agent_addr,
             "alive": n.alive, "resources": n.resources_total,
             "available": n.resources_available, "labels": n.labels,
             "is_head": n.is_head, "draining": n.draining,
             "drain_deadline": n.drain_deadline,
             "drain_reason": n.drain_reason,
             "worker_pool": dict(n.worker_pool)}
            for n in self.nodes.values()
        ]

    async def resource_view(self, _p):
        """Scheduling snapshot used by agents for spillback decisions.
        Draining nodes are excluded — spilling work onto a node about
        to die just converts an announced failure into a surprise
        one."""
        return {
            n.node_id: {"available": n.resources_available,
                        "total": n.resources_total,
                        "agent_addr": n.agent_addr}
            for n in self.nodes.values() if n.alive and not n.draining
        }

    def _resolve_node(self, ref) -> Optional[NodeEntry]:
        """Resolve a node by NodeID or hex prefix (CLI convenience)."""
        node = self.nodes.get(ref)
        if node is not None:
            return node
        if isinstance(ref, str) and ref:
            matches = [n for nid, n in self.nodes.items()
                       if nid.hex().startswith(ref)]
            if len(matches) == 1:
                return matches[0]
        return None

    async def drain_node(self, p):
        """Drain a node (operator `rt drain <node>` or the autoscaler's
        if_idle reap): marks the controller's node row immediately and
        forwards the drain to the agent, which stops granting leases
        and redirects its queue.  ``node_id`` may be a NodeID or a hex
        prefix."""
        node = self._resolve_node(p.get("node_id"))
        if node is None:
            return {"ok": False, "error": "unknown node"}
        if_idle = p.get("if_idle", False)
        reason = p.get("reason") or (
            "idle timeout" if if_idle else "operator drain")
        grace_s = p.get("grace_s") or 0.0
        r = None
        cli = await self._agent(node.node_id)
        if cli is not None:
            try:
                r = await cli.call("drain", {
                    "if_idle": if_idle, "reason": reason,
                    "grace_s": grace_s or None,
                    "replace": p.get("replace", not if_idle)})
            except RpcError:
                r = None
        if r is None:
            # The agent never acknowledged: marking the row anyway
            # would split-brain — the agent keeps granting leases
            # while the controller excludes it, advertises phantom
            # replacement demand, and (drains being one-way) nothing
            # ever reconciles.  Fail the drain; the operator retries.
            return {"ok": False,
                    "error": "agent unreachable; node NOT drained"}
        if not r.get("ok"):
            return r  # agent refused (if_idle race) — stay undrained
        # Mark the row NOW — the agent's heartbeat confirms within a
        # period, but callers (doctor, the trainer's drain poll) must
        # see the state immediately.  The agent's own node_draining
        # callback usually beat us here (fired inside its drain
        # handler); the hooks run once either way.
        first = not node.draining
        node.draining = True
        node.drain_reason = reason
        remaining = r.get("remaining_s") or grace_s or \
            self.config.preemption_grace_s
        node.drain_deadline = time.time() + remaining
        node.drain_replace = p.get("replace", not if_idle)
        if first:
            await self._on_node_draining(node)
        return {"ok": True, "draining": True,
                "node_id": node.node_id.hex(),
                "deadline": node.drain_deadline}

    async def node_draining(self, p):
        """Agent-initiated drain notice (SIGTERM / preemption signal):
        mark the row and kick the migration hooks without waiting for
        the next heartbeat — the grace window can be seconds."""
        node = self.nodes.get(p["node_id"])
        if node is None:
            return {"ok": False}
        first = not node.draining
        node.draining = True
        node.drain_reason = p.get("reason", "")
        remaining = p.get("remaining_s")
        node.drain_deadline = (time.time() + float(remaining)
                               if remaining is not None
                               else p.get("deadline", 0.0))
        node.drain_replace = p.get("replace", True)
        if first:
            await self._on_node_draining(node)
        return {"ok": True}

    async def _on_node_draining(self, node: NodeEntry) -> None:
        logger.warning("node %s DRAINING (%s), deadline %s",
                       node.node_id.hex()[:8], node.drain_reason,
                       node.drain_deadline)
        self._publish("node", {"node_id": node.node_id,
                               "state": "DRAINING",
                               "reason": node.drain_reason,
                               "deadline": node.drain_deadline})
        # Placement groups with bundles on the node are marked for
        # migration (rescheduling happens on death — yanking bundles
        # out from under a live gang would kill the very training run
        # the drain window exists to checkpoint).
        if self._placement is not None:
            self._placement.on_node_draining(node.node_id)

    async def _health_loop(self) -> None:
        period = self.config.raylet_heartbeat_period_ms / 1000.0
        threshold = period * self.config.health_check_failure_threshold
        while not self._shutdown.is_set():
            await asyncio.sleep(period)
            now = time.time()
            for node in list(self.nodes.values()):
                if node.alive and now - node.last_heartbeat > threshold:
                    await self._mark_node_dead(node, "missed heartbeats")

    async def _mark_node_dead(self, node: NodeEntry, reason: str) -> None:
        node.alive = False
        self._job_usage_by_node.pop(node.node_id.hex(), None)
        logger.warning("node %s dead: %s", node.node_id.hex()[:8], reason)
        self._publish("node", {"node_id": node.node_id, "state": "DEAD"})
        # Fail or restart every actor that lived there.
        for actor in list(self.actors.values()):
            if actor.node_id == node.node_id and actor.state in (ALIVE,
                                                                 PENDING):
                await self._handle_actor_failure(
                    actor, f"node {node.node_id.hex()[:8]} died")
        # Drop object locations on that node.  Entries that lose their
        # last copy are KEPT (with empty nodes) so borrower/owner state
        # survives lineage reconstruction; locate_object reports them as
        # location-less.  Fully-idle entries are dropped.
        gone = []
        for oid, info in self.object_dir.items():
            info["nodes"].discard(node.node_id)
            if not info["nodes"]:
                gone.append(oid)
        for oid in gone:
            self._publish("object_lost", {"object_id": oid})
            info = self.object_dir[oid]
            if not info["borrowers"] and not info.get("induced"):
                del self.object_dir[oid]
        if self._placement is not None:
            await self._placement.on_node_dead(node.node_id)

    # ---------------------------------------------------------------- actors
    async def register_actor(self, p):
        """Called by the owner before scheduling the creation task."""
        spec = p["spec"]
        entry = ActorEntry(
            actor_id=spec.actor_id, state=PENDING,
            class_name=p["class_name"], method_names=p["method_names"],
            name=spec.actor_name, namespace=spec.namespace,
            restarts_remaining=spec.max_restarts,
            creation_spec=spec, owner_addr=p.get("owner_addr", ""),
            detached=p.get("detached", False),
            max_concurrency=spec.max_concurrency)
        key = (spec.namespace, spec.actor_name)
        if spec.actor_name:
            if key in self.named_actors:
                return {"ok": False,
                        "error": f"actor name {spec.actor_name!r} taken"}
            self.named_actors[key] = spec.actor_id
        self.actors[spec.actor_id] = entry
        self._mark_dirty()
        return {"ok": True}

    async def register_actors(self, p):
        """Bulk actor registration (owner-side 5 ms coalescing window):
        a 100-actor fan-out costs a handful of controller round trips
        instead of one per actor.  Per-item results keep the single-
        registration semantics (incl. name-conflict refusal)."""
        return {"results": [await self.register_actor(item)
                            for item in p.get("items") or []]}

    async def actors_started(self, p):
        """Bulk actor-started hellos (agent-side coalescing relay) —
        the fan-in half of the fast path register_actors opens."""
        return {"results": [await self.actor_started(item)
                            for item in p.get("items") or []]}

    async def actor_started(self, p):
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return {"ok": False}
        if actor.state == DEAD:
            # Killed while still starting; tell the worker to exit.
            return {"ok": False, "kill": True}
        if actor.state == ALIVE and actor.worker_addr and \
                actor.worker_addr != p["worker_addr"]:
            # First registration wins (ref: gcs_actor_manager single-
            # instance invariant): a duplicate creation attempt — the
            # owner retried after a transient connection loss while
            # the first attempt's __init__ was still running — must
            # exit instead of clobbering the live instance's address.
            return {"ok": False, "kill": True}
        actor.state = ALIVE
        actor.node_id = p["node_id"]
        actor.worker_addr = p["worker_addr"]
        self._publish("actor", {"actor_id": actor.actor_id, "state": ALIVE,
                                "worker_addr": actor.worker_addr})
        return {"ok": True}

    async def actor_died(self, p):
        """Agent-reported worker exit for an actor (crash or kill)."""
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return {"ok": False}
        if p.get("creation_failed"):
            actor.restarts_remaining = 0
        await self._handle_actor_failure(
            actor, p.get("reason", "worker exited"),
            no_restart=p.get("no_restart", False))
        return {"ok": True}

    async def _handle_actor_failure(self, actor: ActorEntry, reason: str,
                                    no_restart: bool = False) -> None:
        if actor.state == DEAD:
            return
        if not no_restart and actor.restarts_remaining != 0:
            if actor.restarts_remaining > 0:
                actor.restarts_remaining -= 1
            actor.state = RESTARTING
            actor.worker_addr = ""
            self._publish("actor", {"actor_id": actor.actor_id,
                                    "state": RESTARTING})
            spawn_task(self._restart_actor(actor))
        else:
            actor.state = DEAD
            actor.death_reason = reason
            actor.worker_addr = ""
            if actor.name:
                self.named_actors.pop((actor.namespace, actor.name), None)
            self._publish("actor", {"actor_id": actor.actor_id,
                                    "state": DEAD, "reason": reason})

    async def _restart_actor(self, actor: ActorEntry) -> None:
        """Re-run the creation spec on a live node (ref:
        gcs_actor_manager.h:553 restart flow)."""
        delay = self.config.task_retry_delay_ms / 1000.0
        for _attempt in range(60):
            await asyncio.sleep(delay)
            for node in self.nodes.values():
                if not node.alive:
                    continue
                cli = await self._agent(node.node_id)
                if cli is None:
                    continue
                try:
                    r = await cli.call("restart_actor",
                                       {"spec": actor.creation_spec})
                    if r.get("ok"):
                        return  # agent will report actor_started
                except RpcError:
                    continue
            delay = min(delay * 2, 2.0)
        await self._handle_actor_failure(actor, "restart failed",
                                         no_restart=True)

    async def get_actor(self, p):
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return None
        spec = actor.creation_spec
        return {"actor_id": actor.actor_id, "state": actor.state,
                "worker_addr": actor.worker_addr,
                "class_name": actor.class_name,
                "method_names": actor.method_names,
                "death_reason": actor.death_reason,
                "max_concurrency": actor.max_concurrency,
                # Name-lookup handles must keep concurrency-group
                # routing (a reconstructed handle falling back to the
                # ordered submit path would reintroduce head-of-line
                # blocking across groups).
                "concurrency_groups":
                    dict(getattr(spec, "concurrency_groups", {}) or {})
                    if spec is not None else {},
                "method_options":
                    dict(getattr(spec, "method_options", {}) or {})
                    if spec is not None else {}}

    async def list_actors(self, _p):
        return [
            {"actor_id": a.actor_id, "state": a.state,
             "class_name": a.class_name, "name": a.name,
             "node_id": a.node_id, "worker_addr": a.worker_addr}
            for a in self.actors.values()
        ]

    async def lookup_named_actor(self, p):
        aid = self.named_actors.get((p.get("namespace", ""), p["name"]))
        if aid is None:
            return None
        return await self.get_actor({"actor_id": aid})

    async def kill_actor(self, p):
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return {"ok": False}
        actor.restarts_remaining = 0 if p.get("no_restart", True) else \
            actor.restarts_remaining
        if actor.node_id is not None:
            cli = await self._agent(actor.node_id)
            if cli is not None:
                aid = actor.actor_id

                async def _kill():
                    try:
                        await cli.call("kill_worker", {"actor_id": aid})
                    except RpcError:
                        pass

                if p.get("no_restart", True):
                    # Off the reply path: a fleet teardown issues
                    # hundreds of kills, and each agent round trip
                    # serialized into the caller's kill() call
                    # dominates teardown time.  Safe only because the
                    # actor id is terminal here — nothing rebinds it.
                    # The SIGKILL itself is asynchronous either way
                    # (death is observed by the agent's reap loop).
                    spawn_task(_kill())
                else:
                    # Restartable: the kill MUST land before the
                    # restart path can bind a fresh worker to the same
                    # actor id, or the late SIGKILL (resolved by
                    # actor_id agent-side) takes down the new
                    # incarnation.
                    await _kill()
        await self._handle_actor_failure(actor, "killed via kill()",
                                         no_restart=p.get("no_restart", True))
        return {"ok": True}

    async def worker_exited(self, p):
        """Generic notification; actor workers route through actor_died."""
        return {"ok": True}

    # -------------------------------------------------------------------- kv
    async def kv_put(self, p):
        overwrite = p.get("overwrite", True)
        if not overwrite and p["key"] in self.kv:
            return {"ok": False, "exists": True}
        self.kv[p["key"]] = p["value"]
        self.kv_list_counts.pop(p["key"], None)  # no longer a list value
        if p["key"].startswith("runtime_env/pkg/"):
            self._touch_pkg(p["key"], len(p["value"]))
        self._publish("kv", {"key": p["key"]})
        return {"ok": True}

    def _touch_pkg(self, key: str, size: int) -> None:
        """LRU cap on runtime-env package blobs: the KV is controller
        memory, and every edited working_dir is a new content digest —
        without eviction a long-lived cluster grows without bound (ref:
        runtime_env URI reference counting / cache GC in
        _private/runtime_env/packaging.py)."""
        from collections import OrderedDict

        lru = getattr(self, "_pkg_lru", None)
        if lru is None:
            lru = self._pkg_lru = OrderedDict()
        lru.pop(key, None)
        lru[key] = size
        cap = self.config.runtime_env_cache_bytes
        while sum(lru.values()) > cap and len(lru) > 1:
            victim, _ = lru.popitem(last=False)
            self.kv.pop(victim, None)
            logger.info("evicted runtime_env package %s (cache > %d)",
                        victim, cap)

    async def kv_get(self, p):
        val = self.kv.get(p["key"])
        if val is not None and p["key"].startswith("runtime_env/pkg/"):
            self._touch_pkg(p["key"], len(val))
        return val

    async def kv_del(self, p):
        self.kv.pop(p["key"], None)
        self.kv_list_counts.pop(p["key"], None)
        self._mark_dirty()
        return {"ok": True}

    async def kv_keys(self, p):
        prefix = p.get("prefix", "")
        return [k for k in self.kv if k.startswith(prefix)]

    async def kv_append(self, p):
        """Atomic append to a list value — rendezvous building block.
        Items are stored length-prefixed so binary values (including NUL
        bytes) round-trip intact; read back with kv_list."""
        key = p["key"]
        cur = self.kv.get(key, b"")
        item = p["value"]
        self.kv[key] = cur + len(item).to_bytes(4, "little") + item
        if key not in self.kv_list_counts:  # key may predate via kv_put
            self.kv_list_counts[key] = len(self._kv_items(key)) - 1
        self.kv_list_counts[key] += 1
        self._publish("kv", {"key": key})
        return {"count": self.kv_list_counts[key]}

    def _kv_items(self, key: str) -> List[bytes]:
        blob = self.kv.get(key, b"")
        items, pos = [], 0
        while pos + 4 <= len(blob):
            n = int.from_bytes(blob[pos:pos + 4], "little")
            pos += 4
            items.append(blob[pos:pos + n])
            pos += n
        return items

    async def kv_list(self, p):
        """Decode a kv_append-built list value into its items."""
        return self._kv_items(p["key"])

    # -------------------------------------------------------- object plane
    def _add_location(self, node_id, oid, size) -> None:
        info = self._dir_entry(oid)  # merges with placeholder borrows
        info["nodes"].add(node_id)
        info["size"] = size

    def _remove_location(self, node_id, oid) -> None:
        info = self.object_dir.get(oid)
        if info is not None:
            info["nodes"].discard(node_id)
            if not info["nodes"]:
                self._drop_if_idle(oid)  # keep borrower/owner state

    async def publish_locations(self, p):
        for oid, size in p["objects"]:
            self._add_location(p["node_id"], oid, size)
        return {"ok": True}

    async def remove_locations(self, p):
        for oid in p["objects"]:
            self._remove_location(p["node_id"], oid)
        return {"ok": True}

    async def update_locations(self, p):
        """Coalesced, ORDERED add/remove location updates from one
        node's agent (the object plane's hot-path publication traffic,
        batched agent-side so a burst of put/release cycles costs one
        frame instead of one call round trip each)."""
        node_id = p["node_id"]
        for kind, item in p["updates"]:
            if kind == "add":
                self._add_location(node_id, item[0], item[1])
            else:
                self._remove_location(node_id, item)
        return {"ok": True}

    async def locate_objects(self, p):
        """Bulk existence probe (wait() fast path): one RPC answers
        readiness for a whole ref list instead of two per ref."""
        out = {}
        for oid in p["object_ids"]:
            info = self.object_dir.get(oid)
            out[oid] = bool(info and info["nodes"])
        return out

    async def locate_object(self, p):
        info = self.object_dir.get(p["object_id"])
        if info is None or not info["nodes"]:
            return None
        nodes = []
        for nid in info["nodes"]:
            node = self.nodes.get(nid)
            if node is not None and node.alive:
                nodes.append({"node_id": nid, "agent_addr": node.agent_addr})
        return {"nodes": nodes, "size": info["size"]}

    async def free_object(self, p):
        oid = p["object_id"]
        info = self.object_dir.pop(oid, None)
        if info is None:
            return {"ok": True}
        for nid in list(info["nodes"]):
            cli = await self._agent(nid)
            if cli is not None:
                try:
                    await cli.notify("delete_object", {"object_id": oid})
                except RpcError:
                    pass
        # Cascade: borrows induced by refs embedded in this object's
        # payload end with the container (the embedded refs can only be
        # materialized out of a payload that no longer exists).
        for emb in info.get("induced", ()):
            await self.remove_borrower({
                "object_id": emb, "holder": f"obj:{oid.hex()}"})
        return {"ok": True}

    # --------------------------------------- distributed reference counting
    # (ref: src/ray/core_worker/reference_count.h:66 — redesigned around
    # this controller's centralized object directory: each process reports
    # only its 0<->1 holder transitions, the controller frees when the
    # owner has released AND no borrowers remain.)
    async def owner_release(self, p):
        """The owning process dropped its last reference."""
        oid = p["object_id"]
        info = self.object_dir.get(oid)
        if info is None:
            return {"ok": True}  # never materialized or already freed
        info["owner_released"] = True
        if not info["borrowers"]:
            await self.free_object({"object_id": oid})
        return {"ok": True}

    def _dir_entry(self, oid: ObjectID) -> Dict:
        """Get-or-create a directory entry.  Borrows may legitimately
        arrive before the object is published (a ref travels in a task
        spec while the producer is still sealing); the placeholder keeps
        the borrow so the eventual publish + owner release can't free the
        object out from under the borrower."""
        info = self.object_dir.get(oid)
        if info is None:
            info = self.object_dir[oid] = {
                "nodes": set(), "size": 0,
                "borrowers": set(), "owner_released": False}
        return info

    def _drop_if_idle(self, oid: ObjectID) -> None:
        info = self.object_dir.get(oid)
        if info is not None and not info["nodes"] \
                and not info["borrowers"] and not info.get("induced"):
            del self.object_dir[oid]

    async def add_borrower(self, p):
        self._dir_entry(p["object_id"])["borrowers"].add(p["holder"])
        return {"ok": True}

    async def remove_borrower(self, p):
        oid = p["object_id"]
        info = self.object_dir.get(oid)
        if info is None:
            return {"ok": True}
        info["borrowers"].discard(p["holder"])
        if info["owner_released"] and not info["borrowers"]:
            await self.free_object({"object_id": oid})
        else:
            self._drop_if_idle(oid)
        return {"ok": True}

    async def link_induced_borrows(self, p):
        """Register borrows held on behalf of refs embedded inside a
        container object's serialized payload; they are released when the
        container is freed (free_object cascade)."""
        container = p["container"]
        holder = f"obj:{container.hex()}"
        for emb in p["embedded"]:
            self._dir_entry(emb)["borrowers"].add(holder)
        cinfo = self._dir_entry(container)
        cinfo.setdefault("induced", set()).update(p["embedded"])
        return {"ok": True}

    # ---------------------------------------------------------------- pubsub
    async def poll_events(self, p):
        """Cursor-based long-poll (ref: src/ray/pubsub long-poll design).
        If the cursor predates trimmed history on any requested channel,
        the reply carries cursor_expired=True: events were lost and the
        subscriber must do a full resync (list_actors/list_nodes)."""
        cursor = p.get("cursor", 0)
        channels = p.get("channels", ["actor", "node"])
        timeout = p.get("timeout", 30.0)
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            # Recomputed each pass: a trim can happen while we long-poll.
            expired = any(cursor < self.events_trimmed_to.get(ch, 0)
                          for ch in channels)
            out = []
            for ch in channels:
                for seq, data in self.events.get(ch, []):
                    if seq > cursor:
                        out.append((seq, ch, data))
            if out or expired:
                out.sort()
                new_cursor = out[-1][0] if out else \
                    max(cursor, self.event_seq)
                return {"events": out, "cursor": new_cursor,
                        "cursor_expired": expired}
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                return {"events": [], "cursor": cursor}
            ev = asyncio.Event()
            self.event_waiters.append(ev)
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                pass
            finally:
                self.event_waiters.remove(ev)

    # ------------------------------------------------------------------ jobs
    # ----------------------------------------------------- task events
    async def worker_logs(self, p):
        """Batched worker log lines from node-agent tailers; fanned to
        drivers over the worker_logs pubsub channel (ref:
        log_monitor.py lines -> GCS pubsub -> driver print)."""
        for rec in p.get("batch", []):
            self._publish("worker_logs", rec)
        return {"ok": True}

    async def task_events(self, p):
        """Batched task state transitions from workers (ref:
        task_event_buffer.h:222 flush -> gcs_task_manager.h:86)."""
        cap = max(self.config.task_event_buffer_size, 16)
        recv_ts = time.time()
        # Owner-side explainability events trimmed before they could
        # flush count as drops too — a gapped `rt explain` chain must
        # be attributable to backpressure, not read as a phantom bug.
        self.task_events_dropped += int(p.get("dropped") or 0)
        hp = p.get("hotpath")
        if hp:
            # Sampled phase-stamp records piggybacked on the owner's
            # event flush — aggregated here, read by `rt hotpath`.
            self.hotpath_sink.add(p.get("source") or "", hp)
        for ev in p["events"]:
            tid = ev["task_id"]
            rec = self.task_records.get(tid)
            if rec is None:
                if len(self.task_records) >= cap:
                    # Evict the oldest finished record first.
                    for k, r in self.task_records.items():
                        if r.get("state") in ("FINISHED", "FAILED"):
                            del self.task_records[k]
                            break
                    else:
                        self.task_records.popitem(last=False)
                    self.task_events_dropped += 1
                rec = self.task_records[tid] = {
                    "task_id": tid, "times": {}}
            rec.update({k: v for k, v in ev.items()
                        if k not in ("task_id", "state", "ts",
                                     "detail", "attempt")})
            state = ev.get("state")
            if state:
                # Owner-side scheduling events (QUEUED/PIPELINED/...)
                # and worker-side execution events flush on different
                # cadences AND carry timestamps from different hosts,
                # so neither arrival order nor raw timestamps resolve
                # the headline state.  Rank by execution attempt
                # first (a retry's events supersede the previous
                # attempt's terminal state), then lifecycle tier
                # (terminal > running > scheduling); timestamps only
                # break ties within the same attempt and tier.
                cur = rec.get("state")
                cur_att = int(rec.get("attempt") or 0)
                new_att = int(ev.get("attempt") or 0)
                cur_tier = _STATE_TIER.get(cur, 1)
                new_tier = _STATE_TIER.get(state, 1)
                if cur is None or new_att > cur_att or (
                        new_att == cur_att
                        and (new_tier > cur_tier
                             or (new_tier == cur_tier
                                 and ev["ts"] >= rec["times"].get(
                                     cur, float("-inf"))))):
                    rec["state"] = state
                    rec["attempt"] = max(cur_att, new_att)
                if new_att >= cur_att:
                    # A late batch from a PREVIOUS attempt must not
                    # roll timestamps back under the current one.
                    rec["times"][state] = ev["ts"]
                    # Receipt-clock shadow: reporter timestamps come
                    # from arbitrary host clocks, so age computations
                    # (the stuck-task detector) use the controller's
                    # receipt time; durations still use the
                    # reporter-clock times (same-host deltas).
                    rec.setdefault("times_recv", {})[state] = recv_ts
                # Full transition chain with reason tags (scheduler
                # explainability: queued -> lease_requested ->
                # pipelined/granted -> running -> finished/requeued),
                # bounded per task so a retry storm can't grow a
                # record without limit.
                chain = rec.setdefault("transitions", [])
                detail = dict(ev.get("detail") or {})
                if new_att:
                    detail["attempt"] = new_att
                chain.append([ev["ts"], state, detail])
                if len(chain) > 64:
                    del chain[:len(chain) - 64]
        self._mark_dirty()
        return {"ok": True}

    async def list_tasks(self, p):
        out = []
        limit = p.get("limit", 1000)
        flt_state = p.get("state")
        flt_name = p.get("name")
        for rec in reversed(self.task_records.values()):
            if flt_state and rec.get("state") != flt_state:
                continue
            if flt_name and rec.get("name") != flt_name:
                continue
            out.append(rec)
            if len(out) >= limit:
                break
        return {"tasks": out, "dropped": self.task_events_dropped,
                "total": len(self.task_records)}

    async def get_task(self, p):
        return self.task_records.get(p["task_id"])

    async def explain_task(self, p):
        """Scheduler explainability: the full transition chain of one
        task (`rt explain <task_id>`; prefix match accepted).  Answers
        *why* a task sat where it did — which lease it pipelined onto,
        which agent queued its lease request, whether it was requeued
        off a blocked worker — without reading agent logs."""
        tid = p.get("task_id") or ""
        rec = self.task_records.get(tid)
        if rec is None and tid:
            matches = [r for t, r in self.task_records.items()
                       if t.startswith(tid)]
            if len(matches) == 1:
                rec = matches[0]
            elif len(matches) > 1:
                return {"ok": False,
                        "error": f"task id prefix {tid!r} is ambiguous "
                                 f"({len(matches)} matches)"}
        if rec is None:
            return {"ok": False, "error": f"no task record {tid!r} "
                                          f"(dropped or never seen)"}
        return {"ok": True, "task": rec}

    # ------------------------------------------------- health plane
    async def collective_entries(self, p):
        """Per-source inflight collective stamps (gang watchdog).
        Replace semantics: each report is the source's CURRENT set."""
        src = p.get("source") or "?"
        now = time.time()
        # Rebase entry times onto the CONTROLLER clock from the
        # reporter's age delta: worker-host wall clocks can be
        # arbitrarily skewed, and the watchdog deadline is small
        # enough that skew alone would forge (or mask) a hang.
        entries = []
        for e in p.get("entries") or []:
            if "age_s" in e:
                e = {**e, "since": now - float(e["age_s"])}
            entries.append(e)
        self.collective_reports[src] = {"ts": now, "entries": entries}
        # Prune dead reporters here too, not just in the doctor-feed
        # merge: under worker churn on a cluster nobody runs `rt
        # doctor` against, the per-source dict would otherwise grow
        # one entry per dead worker forever.
        self._prune_collective_reports(now)
        return {"ok": True}

    def _collective_horizon(self) -> float:
        return max(self.config.metrics_report_period_s * 3, 5.0)

    def _prune_collective_reports(self, now: float) -> None:
        horizon = self._collective_horizon()
        for src in [s for s, v in list(self.collective_reports.items())
                    if now - v["ts"] > horizon * 4]:
            del self.collective_reports[src]  # dead reporter

    def _merged_collective_inflight(self, now: float) -> List[Dict]:
        """Merge fresh per-source stamps into one row per (group,
        seq): which ranks are inside, since when, expecting how many."""
        horizon = self._collective_horizon()
        merged: Dict[Tuple[str, int], Dict] = {}
        self._prune_collective_reports(now)
        for src, rep in self.collective_reports.items():
            if now - rep["ts"] > horizon:
                continue  # stale: the process stopped refreshing
            for e in rep["entries"]:
                key = (e.get("group", "?"), int(e.get("seq", 0)))
                rec = merged.get(key)
                if rec is None:
                    rec = merged[key] = {
                        "group": key[0], "seq": key[1],
                        "op": e.get("op", "?"),
                        "backend": e.get("backend", "?"),
                        "world": int(e.get("world", 0)),
                        "ranks": {}}
                rec["ranks"][int(e.get("rank", -1))] = \
                    float(e.get("since", now))
        return list(merged.values())

    async def report_autoscaler_decision(self, p):
        self.autoscaler_decisions.append({
            "ts": p.get("ts") or time.time(),
            "demands": p.get("demands", 0),
            "launched": list(p.get("launched") or []),
            "terminated": list(p.get("terminated") or []),
            "preempted": list(p.get("preempted") or []),
            "unsatisfied": list(p.get("unsatisfied") or [])})
        return {"ok": True}

    async def doctor_feed(self, _p):
        """One-stop raw feed for `rt doctor` / /api/doctor: the
        health-plane state only the controller holds.  The client
        (util/doctor.py) combines it with the regular state RPCs."""
        now = time.time()
        return {
            "ts": now,
            "collective_inflight": self._merged_collective_inflight(
                now),
            "autoscaler_decisions": list(self.autoscaler_decisions),
            "flight": list(self.flight_dumps.values()),
            "task_events_dropped": self.task_events_dropped,
        }

    async def list_objects(self, p):
        out = []
        limit = p.get("limit", 1000)
        for oid, info in self.object_dir.items():
            out.append({
                "object_id": oid.hex() if hasattr(oid, "hex") else str(oid),
                "size": info.get("size", 0),
                "nodes": [n.hex() if hasattr(n, "hex") else str(n)
                          for n in info.get("nodes", ())],
            })
            if len(out) >= limit:
                break
        return {"objects": out, "total": len(self.object_dir)}

    async def list_jobs(self, p):
        return {"jobs": [dict(j, job_id=jid)
                         for jid, j in self.jobs.items()]}

    # ------------------------------------------------- multi-tenant jobs
    async def job_register(self, p):
        """Register a submitted job's multi-tenant metadata (priority,
        optional quota) — called by the job supervisor before the
        entrypoint spawns, so admission/quota decisions never race the
        job's first lease request."""
        job_id = p["job_id"]
        quota = p.get("quota") or None
        if quota is not None:
            quota = {str(k): float(v) for k, v in quota.items()}
        self.job_plane[job_id] = {
            "job_id": job_id,
            "priority": int(p.get("priority") or 0),
            "quota": quota,
            "entrypoint": p.get("entrypoint", ""),
            "submitted": p.get("ts") or time.time(),
        }
        self._publish("job", {"job_id": job_id, "state": "REGISTERED",
                              "priority": self.job_plane[job_id]
                              ["priority"]})
        return {"ok": True}

    def _tenant_of_hex(self, job_hex: str) -> str:
        """Map an internal driver job hex to its tenant job id."""
        cache = getattr(self, "_tenant_cache", None)
        if cache is None:
            cache = self._tenant_cache = {}
        hit = cache.get(job_hex)
        if hit is not None:
            return hit
        for jid, rec in self.jobs.items():
            h = JobID.from_int(jid).hex()
            cache[h] = rec.get("tenant", "")
        return cache.get(job_hex, "")

    def _job_usage(self, job_id: str,
                   exclude_pg=None) -> Dict[str, float]:
        """Cluster-wide resource usage attributed to one tenant job:
        committed placement-group bundles (controller's own books) +
        agent-reported plain leases (heartbeat overlay)."""
        used: Dict[str, float] = {}
        if self._placement is not None:
            for entry in self._placement._groups.values():
                if getattr(entry, "job", "") != job_id or \
                        entry.state != "CREATED" or \
                        entry.pg_id == exclude_pg:
                    continue
                for b in entry.bundles:
                    for k, v in b.items():
                        used[k] = used.get(k, 0.0) + v
        for per_job in self._job_usage_by_node.values():
            for job_hex, res in per_job.items():
                if self._tenant_of_hex(job_hex) != job_id:
                    continue
                for k, v in res.items():
                    used[k] = used.get(k, 0.0) + v
        return used

    def _job_is_terminal(self, job_id: str) -> bool:
        import json as _json

        raw = self.kv.get(f"job/{job_id}/status")
        if not raw:
            return False
        try:
            return _json.loads(raw).get("status") in (
                "SUCCEEDED", "FAILED", "STOPPED")
        except (ValueError, TypeError):
            return False

    def _job_quota_view(self) -> Dict[str, Dict]:
        """The per-internal-job view shipped to agents in heartbeat
        replies: only jobs whose tenant registered a quota or a
        non-zero priority (keeps the common single-tenant heartbeat
        payload empty).  Terminal tenants and dead drivers are
        skipped — they can request nothing, and without the filter
        the view (computed per heartbeat, shipped to every agent)
        would grow with job history forever."""
        if not self.job_plane:
            return {}
        interesting = {j: rec for j, rec in self.job_plane.items()
                       if (rec.get("quota") or rec.get("priority"))
                       and not self._job_is_terminal(j)}
        if not interesting:
            return {}
        out: Dict[str, Dict] = {}
        usage_cache: Dict[str, Dict[str, float]] = {}
        for jid, rec in self.jobs.items():
            if not rec.get("alive", True):
                continue  # a dead driver can't request leases
            tenant = rec.get("tenant", "")
            plane = interesting.get(tenant)
            if plane is None:
                continue
            if tenant not in usage_cache:
                usage_cache[tenant] = self._job_usage(tenant)
            out[JobID.from_int(jid).hex()] = {
                "job": tenant,
                "priority": plane["priority"],
                "quota": plane.get("quota"),
                "used": usage_cache[tenant],
            }
        return out

    async def jobs_overview(self, p):
        """`rt jobs` / /api/jobs: every submitted job with priority,
        quota, live resource usage, state, and submission time.
        ``job_id`` prefix-filters (the `rt explain` convention)."""
        prefix = (p or {}).get("job_id") or ""
        import json as _json

        ids = set(self.job_plane)
        for key in self.kv:
            if key.startswith("job/") and key.endswith("/status"):
                ids.add(key.split("/", 2)[1])
        rows = []
        for job_id in sorted(ids):
            if prefix and not job_id.startswith(prefix):
                continue
            plane = self.job_plane.get(job_id, {})
            status: Dict[str, Any] = {}
            raw = self.kv.get(f"job/{job_id}/status")
            if raw:
                try:
                    status = _json.loads(raw)
                except (ValueError, TypeError):
                    status = {}
            row = {
                "job_id": job_id,
                "priority": plane.get("priority", 0),
                "quota": plane.get("quota"),
                "usage": self._job_usage(job_id),
                "state": status.get("status", "?"),
                "message": status.get("message", ""),
                "entrypoint": status.get("entrypoint")
                or plane.get("entrypoint", ""),
                "submitted": plane.get("submitted")
                or status.get("ts", 0.0),
            }
            pre = self.preempting.get(job_id)
            if pre is not None:
                row["preempting"] = {
                    "reason": pre.get("reason", ""),
                    "by": pre.get("by", ""),
                    "remaining_s": max(pre["deadline"] - time.time(),
                                       0.0)}
            rows.append(row)
        return {"jobs": rows}

    async def preempt_job(self, p):
        """Mark a job for preemption: the victim's trainer observes it
        on its drain-poll cadence (checkpoint-on-notice inside the
        grace window); at the deadline the enforcement loop evicts the
        job's placement groups, so the gang dies as an ANNOUNCED
        failure and restarts from the notice checkpoint."""
        job_id = p["job_id"]
        if job_id in self.preempting:
            return {"ok": True, "already": True,
                    "deadline": self.preempting[job_id]["deadline"]}
        grace = p.get("grace_s")
        if grace is None:  # explicit 0 means evict immediately
            grace = self.config.preemption_grace_s
        rec = {"job_id": job_id, "reason": p.get("reason", "preempted"),
               "by": p.get("by", ""), "ts": time.time(),
               "deadline": time.time() + max(float(grace), 0.0)}
        self.preempting[job_id] = rec
        logger.warning("job %s preempting (%s): grace %.1fs",
                       job_id, rec["reason"], grace)
        self._publish("job", {"job_id": job_id, "state": "PREEMPTING",
                              "reason": rec["reason"],
                              "deadline": rec["deadline"]})
        return {"ok": True, "deadline": rec["deadline"]}

    async def job_preemption_state(self, p):
        """Polled by the victim's trainer driver (its drain-poll
        cadence): the deadline crosses hosts as REMAINING seconds, the
        same clock discipline as node drains."""
        rec = self.preempting.get(p.get("job_id") or "")
        if rec is None:
            return {"preempting": False}
        return {"preempting": True,
                "reason": rec.get("reason", ""),
                "by": rec.get("by", ""),
                "remaining_s": max(rec["deadline"] - time.time(), 0.0)}

    async def _job_preemption_loop(self) -> None:
        """Enforce preemption deadlines: once the grace expires, evict
        the victim's placement groups (killing the gang workers), so
        capacity frees for the admission loop's next pass.  The notice
        is cleared BEFORE enforcement — the victim's next attempt must
        not see a stale interrupt and checkpoint-on-notice forever."""
        while not self._shutdown.is_set():
            await asyncio.sleep(0.25)
            now = time.time()
            for job_id, rec in list(self.preempting.items()):
                if now < rec["deadline"]:
                    continue
                del self.preempting[job_id]
                self._tenant_cache = {}
                logger.warning("job %s preemption grace expired; "
                               "evicting its gangs", job_id)
                self._publish("job", {"job_id": job_id,
                                      "state": "PREEMPTED",
                                      "reason": rec.get("reason", "")})
                self.autoscaler_decisions.append({
                    "ts": now, "demands": 0, "launched": [],
                    "terminated": [], "unsatisfied": [],
                    "preempted": [f"job:{job_id}"]})
                if self._placement is not None:
                    try:
                        await self._placement.preempt_job_groups(
                            job_id, reason=rec.get("reason", ""))
                    except Exception:
                        logger.exception("preemption enforcement for "
                                         "job %s failed", job_id)

    # --------------------------------------------------------- metrics
    async def report_metrics(self, p):
        now = time.time()
        self.metrics_sources[p["source"]] = {
            "snapshot": p["snapshot"], "ts": now}
        # Bounded per-source history for dashboard time series (ref:
        # dashboard/modules/reporter/ — utilization over time, not
        # just the current snapshot).  ~30 min at the default 5 s
        # report period; never persisted.
        from collections import deque

        hist = getattr(self, "_metrics_history", None)
        if hist is None:
            hist = self._metrics_history = {}
        flat: Dict[str, float] = {}
        for metric in p["snapshot"]:
            for s in metric.get("series", []):
                tags = s.get("tags") or {}
                key = metric["name"]
                if tags:
                    key += "{" + ",".join(
                        f"{k}={v}" for k, v in sorted(tags.items())) \
                        + "}"
                if "value" in s:
                    flat[key] = float(s["value"])
                elif "hist" in s:
                    # Histogram series flatten to their running count
                    # and sum — enough for rate/mean time series.
                    flat[key + "_count"] = float(s["hist"]["count"])
                    flat[key + "_sum"] = float(s["hist"]["sum"])
        dq = hist.get(p["source"])
        if dq is None:
            dq = hist[p["source"]] = deque(maxlen=360)
        dq.append((now, flat))
        return {"ok": True}

    async def report_flight_dump(self, p):
        """A node agent forwards a dead worker's flight-recorder dump
        (ref: the reference's dashboard event aggregation; here the
        postmortem ring of a reaped process)."""
        src = p.get("source") or "?"
        self.flight_dumps[src] = {
            "source": src, "reason": p.get("reason", ""),
            # Receipt-clock shadow (same discipline as task times):
            # the dump's own ts is the DYING WORKER's wall clock, not
            # comparable with the controller clock ages are computed
            # against.
            "ts": p.get("ts"), "ts_recv": time.time(),
            "path": p.get("path", ""),
            "sticky": p.get("sticky") or {},
            "events": (p.get("events") or [])[-200:]}
        self.flight_dumps.move_to_end(src)
        while len(self.flight_dumps) > 32:
            self.flight_dumps.popitem(last=False)
        return {"ok": True}

    async def report_spans(self, p):
        """Span records drained from a process's ring (relayed by its
        node agent, or pushed directly by the driver).  The sink is one
        bounded deque — oldest spans fall off first, same policy as the
        task-event sink."""
        src = p.get("source") or "?"
        node = p.get("node_id")
        for s in p.get("spans") or []:
            s.setdefault("source", src)
            if node and not s.get("node_id"):
                s["node_id"] = node
            self.span_records.append(s)
            self.spans_received += 1
            # Finished ingress spans feed the slow-request exemplar
            # ring (request id + duration + deployment + dominant-
            # phase inputs live in the sink for assembly on demand).
            if s.get("name") == "ingress":
                tags = s.get("tags") or {}
                rid = tags.get("request_id")
                if rid:
                    try:
                        self.request_exemplar_ring.offer(
                            rid,
                            max(float(s.get("end", 0.0))
                                - float(s.get("start", 0.0)), 0.0),
                            deployment=tags.get("deployment", "?"),
                            ts=time.time(),
                            outcome=tags.get("outcome", "?"),
                            status_class=tags.get("status_class", "?"))
                    except Exception:
                        pass  # observability must never fail the relay
        return {"ok": True}

    async def request_exemplars(self, p):
        """Slowest-request exemplars in the current window (slowest
        first) — the `rt trace` listing and find_slow_requests feed."""
        return {"exemplars": self.request_exemplar_ring.snapshot(),
                "window_s": self.request_exemplar_ring.window_s}

    async def list_spans(self, p):
        limit = (p or {}).get("limit", 10000)
        cat = (p or {}).get("cat")
        out = []
        for s in reversed(self.span_records):
            if cat and s.get("cat") != cat:
                continue
            out.append(s)
            if len(out) >= limit:
                break
        out.reverse()  # chronological-ish (ring append order)
        return {"spans": out, "total": len(self.span_records),
                "received": self.spans_received}

    async def report_profile(self, p):
        """A node agent reports a finished on-demand profiler capture
        (artifact stays on the node's disk; this records where)."""
        self.profile_artifacts.append({
            "source": p.get("source", "?"), "kind": p.get("kind", "jax"),
            "path": p.get("path", ""), "node_id": p.get("node_id"),
            "ts": p.get("ts") or time.time()})
        return {"ok": True}

    def _prune_metrics_sources(self, now: float) -> None:
        """Drop sources that stopped reporting (dead workers/nodes) —
        a gauge from a dead process must not render as current, and
        the map must not grow with worker churn."""
        horizon = max(self.config.metrics_report_period_s * 6, 30.0)
        for src in [s for s, v in self.metrics_sources.items()
                    if now - v["ts"] > horizon]:
            del self.metrics_sources[src]

    async def hotpath(self, p):
        """Cluster-wide hot-path phase decomposition: aggregated
        sampled task stamp records (`rt hotpath`, /api/hotpath)."""
        return self.hotpath_sink.snapshot()

    def _self_metric_snaps(self):
        """Controller-process introspection rendered in registry
        snapshot shape: its own event-loop lag, RPC handler stats and
        the cluster-wide task-event drop counter — so the controller
        shows up in telemetry/doctor like any other reporting source."""
        snaps = [
            {"name": "rt_task_events_dropped_total", "kind": "counter",
             "description": "Task lifecycle events dropped cluster-wide"
                            " (owner-side trims + controller evictions).",
             "series": [{"tags": {},
                         "value": float(self.task_events_dropped)}]},
        ]
        lag = getattr(self, "_loop_lag", None)
        if lag is not None:
            snaps.extend(lag.metric_snaps())
        snaps.extend(self.server.stats.metric_snaps())
        return snaps

    async def telemetry(self, p):
        """Raw telemetry feed for `rt telemetry` / /api/telemetry:
        latest per-source metric snapshots + retained flight dumps.
        Aggregation happens client-side (util/telemetry.py)."""
        now = time.time()
        self._prune_metrics_sources(now)
        sources = {s: v["snapshot"]
                   for s, v in self.metrics_sources.items()}
        # The controller reports itself inline — it has no agent to
        # piggyback on, and its loop lag / RPC stats are exactly what
        # the doctor's stall and convoy finders need to see.
        sources["controller"] = self._self_metric_snaps()
        return {"ts": now,
                "sources": sources,
                "flight": list(self.flight_dumps.values()),
                "profiles": list(self.profile_artifacts)}

    def _prune_metrics_history(self, now: float) -> None:
        """Dead sources must not leak deques under worker churn (the
        same contract metrics_sources keeps)."""
        hist = getattr(self, "_metrics_history", None)
        if not hist:
            return
        horizon = max(self.config.metrics_report_period_s * 6, 30.0)
        for src in [s for s, dq in hist.items()
                    if not dq or now - dq[-1][0] > horizon]:
            del hist[src]

    async def metrics_history(self, p):
        """Per-source time series: {source: [[ts, {metric: value}],
        ...]} (ref: dashboard reporter plane)."""
        hist = getattr(self, "_metrics_history", {})
        self._prune_metrics_history(time.time())
        want = (p or {}).get("source")
        out = {}
        for src, dq in hist.items():
            if want and src != want:
                continue
            out[src] = [[ts, vals] for ts, vals in dq]
        return out

    async def metrics_text(self, _p):
        from ray_tpu.util.metrics import render_prometheus

        now = time.time()
        self._prune_metrics_sources(now)
        self._prune_metrics_history(now)
        sources = {s: v["snapshot"]
                   for s, v in self.metrics_sources.items()}
        # Controller-internal gauges, rendered with the same pipeline.
        alive = sum(1 for n in self.nodes.values() if n.alive)
        internal = [
            {"name": "rt_nodes_alive", "kind": "gauge",
             "description": "Alive node agents.",
             "series": [{"tags": {}, "value": alive}]},
            {"name": "rt_nodes_total", "kind": "gauge",
             "description": "Ever-registered node agents.",
             "series": [{"tags": {}, "value": len(self.nodes)}]},
            {"name": "rt_actors", "kind": "gauge",
             "description": "Actors by state.",
             "series": [{"tags": {"state": s},
                         "value": sum(1 for a in self.actors.values()
                                      if a.state == s)}
                        for s in ("ALIVE", "PENDING", "RESTARTING",
                                  "DEAD")]},
            {"name": "rt_tasks_recorded", "kind": "gauge",
             "description": "Task records retained.",
             "series": [{"tags": {}, "value": len(self.task_records)}]},
            {"name": "rt_objects_tracked", "kind": "gauge",
             "description": "Objects in the cluster directory.",
             "series": [{"tags": {}, "value": len(self.object_dir)}]},
        ]
        internal.extend(self._self_metric_snaps())
        sources["controller"] = internal
        return {"text": render_prometheus(sources)}

    async def register_job(self, p):
        jid = self.job_counter
        self.job_counter += 1
        self.jobs[jid] = {"start": time.time(), "driver": p.get("driver", ""),
                          "alive": True,
                          # Link to the multi-tenant job plane: the
                          # submitted job's entrypoint driver carries
                          # its RT_JOB_ID here, so leases/PGs tagged
                          # with the internal job hex resolve to the
                          # tenant for quota/priority/attribution.
                          "tenant": p.get("tenant", "")}
        self._mark_dirty()
        return {"job_id": jid}

    async def finish_job(self, p):
        job = self.jobs.get(p["job_id"])
        if job:
            job["alive"] = False
            self._mark_dirty()
        # Non-detached actors die with their job's driver (ref:
        # gcs_actor_manager.cc OnJobFinished -> DestroyActor) — without
        # this, every connect-and-disconnect driver leaks its actors'
        # workers and their CPU leases into the shared cluster.
        from .ids import JobID

        jid = JobID.from_int(p["job_id"])
        reaped = 0
        for actor in list(self.actors.values()):
            spec = actor.creation_spec
            if actor.detached or spec is None or actor.state == DEAD:
                continue
            if spec.job_id == jid:
                await self.kill_actor({"actor_id": actor.actor_id,
                                       "no_restart": True})
                reaped += 1
        if reaped:
            logger.info("job %s finished: reaped %d actors",
                        p["job_id"], reaped)
        return {"ok": True, "actors_reaped": reaped}

    # ------------------------------------------------------ placement groups
    async def create_placement_group(self, p):
        return await self._placement.create(p)

    async def remove_placement_group(self, p):
        return await self._placement.remove(p)

    async def get_placement_group(self, p):
        return self._placement.get(p)

    async def list_placement_groups(self, p):
        return self._placement.list_all(p)

    # -------------------------------------------------------------- lifetime
    async def ping(self, _p):
        return {"ok": True, "session": self.session,
                "time": time.time()}

    async def cluster_shutdown(self, _p):
        for node in self.nodes.values():
            cli = await self._agent(node.node_id)
            if cli is not None:
                try:
                    await cli.notify("shutdown", {})
                except RpcError:
                    pass
        asyncio.get_event_loop().call_later(0.2, self._shutdown.set)
        return {"ok": True}

    async def run(self, port: int = 0, driver_pid: int = 0) -> int:
        from .placement import PlacementGroupManager

        self._placement = PlacementGroupManager(self)
        if self.config.controller_persistence_enabled:
            self._snapshot_path = os.path.join(
                self.config.session_dir_root, self.session,
                "controller_state.pkl")
            self._load_snapshot()
            spawn_task(self._persist_loop())
        await self.server.start(port)
        # Event-loop lag sampler: the controller loop stalling is the
        # single worst control-plane failure mode (every RPC convoys
        # behind it), so it self-measures like workers/agents do.
        from ray_tpu.util.hotpath import LoopLagSampler

        self._loop_lag = LoopLagSampler(asyncio.get_event_loop())
        self._loop_lag.start()
        spawn_task(self._health_loop())
        spawn_task(self._job_preemption_loop())
        if driver_pid:
            spawn_task(self._watch_driver(driver_pid))
        return self.server.port

    # ------------------------------------------- persistence (GCS FT)
    # Ref: gcs_server.h:113 StorageType + Redis-backed tables; redesigned
    # as a debounced whole-state snapshot — controller state at TPU-host
    # granularity is kilobytes, so one atomic pickle beats a table store.
    def _mark_dirty(self) -> None:
        self._dirty = True

    _PERSIST_CHANNELS = ("actor", "node", "kv", "placement_group",
                         "object_lost")

    def _snapshot_state(self) -> Dict[str, Any]:
        pgs = []
        if self._placement is not None:
            for e in self._placement._groups.values():
                pgs.append({
                    "pg_id": e.pg_id, "bundles": e.bundles,
                    "strategy": e.strategy, "state": e.state,
                    "name": e.name, "placement": dict(e.placement),
                    "priority": e.priority, "job": e.job,
                    "create_time": e.create_time})
        return {
            "kv": self.kv, "kv_list_counts": self.kv_list_counts,
            "actors": self.actors, "named_actors": self.named_actors,
            "jobs": self.jobs, "job_counter": self.job_counter,
            "job_plane": self.job_plane,
            "preempting": self.preempting,
            "task_records": self.task_records,
            "task_events_dropped": self.task_events_dropped,
            "event_seq": self.event_seq,
            "placement_groups": pgs,
        }

    async def _persist_loop(self) -> None:
        import pickle

        self._dirty = True
        while not self._shutdown.is_set():
            await asyncio.sleep(0.5)
            if not getattr(self, "_dirty", False):
                continue
            self._dirty = False
            try:
                data = pickle.dumps(self._snapshot_state())
                tmp = self._snapshot_path + ".tmp"

                def _write():
                    os.makedirs(os.path.dirname(self._snapshot_path),
                                exist_ok=True)
                    with open(tmp, "wb") as f:
                        f.write(data)
                    os.replace(tmp, self._snapshot_path)

                await asyncio.get_event_loop().run_in_executor(None,
                                                               _write)
            except Exception:
                # Persistence must degrade loudly, not die silently: a
                # frozen snapshot restores arbitrarily stale state.
                logger.exception("controller snapshot failed; retrying "
                                 "next cycle")
                self._dirty = True

    def _load_snapshot(self) -> None:
        import pickle

        try:
            with open(self._snapshot_path, "rb") as f:
                state = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError):
            return
        self.kv = state["kv"]
        self.kv_list_counts = state["kv_list_counts"]
        self.actors = state["actors"]
        self.named_actors = state["named_actors"]
        self.jobs = state["jobs"]
        self.job_counter = state["job_counter"]
        self.job_plane = state.get("job_plane", {})
        self.preempting = state.get("preempting", {})
        self.task_records = state["task_records"]
        self.task_events_dropped = state["task_events_dropped"]
        # Event history is gone: continue the sequence and mark all of
        # it trimmed, so every live subscriber gets cursor_expired and
        # resyncs instead of silently missing transitions.
        self.event_seq = state["event_seq"]
        for ch in self._PERSIST_CHANNELS:
            self.events_trimmed_to[ch] = self.event_seq
        from .placement import PGEntry

        for rec in state["placement_groups"]:
            entry = PGEntry(pg_id=rec["pg_id"], bundles=rec["bundles"],
                            strategy=rec["strategy"], state=rec["state"],
                            name=rec["name"],
                            priority=rec.get("priority", 0),
                            job=rec.get("job", ""))
            if rec.get("create_time"):
                entry.create_time = rec["create_time"]
            entry.placement = rec["placement"]
            self._placement._groups[rec["pg_id"]] = entry
        # Restored PENDING/RESCHEDULING groups need the admission loop
        # running again (the pre-restart loop died with the process).
        self._placement.kick()
        logger.info("restored controller state: %d actors, %d kv keys, "
                    "%d jobs, %d PGs", len(self.actors), len(self.kv),
                    len(self.jobs), len(state["placement_groups"]))

    async def _watch_driver(self, pid: int) -> None:
        """Head clusters spawned by a driver die with it (atexit handles
        clean exits; this covers SIGKILL so nothing orphans a 1-core
        host).  Clusters started standalone pass no pid and outlive
        drivers the way the reference's do."""
        while not self._shutdown.is_set():
            await asyncio.sleep(2.0)
            try:
                os.kill(pid, 0)
            except OSError:
                logger.warning("owning driver %d is gone; shutting down",
                               pid)
                await self.cluster_shutdown(None)
                return

    async def wait_shutdown(self) -> None:
        await self._shutdown.wait()
        lag = getattr(self, "_loop_lag", None)
        if lag is not None:
            lag.stop()
        await self.server.stop()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session", required=True)
    parser.add_argument("--ready-fd", type=int, default=-1)
    parser.add_argument("--driver-pid", type=int, default=0)
    args = parser.parse_args()
    logging.basicConfig(
        level=getattr(logging,
                      os.environ.get("RT_LOG_LEVEL", "INFO").upper(),
                      logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    config = RuntimeConfig.from_env()

    async def _run():
        ctl = Controller(config, args.session)
        port = await ctl.run(args.port, driver_pid=args.driver_pid)
        if args.ready_fd >= 0:
            os.write(args.ready_fd, f"{ctl.server.address}\n".encode())
            os.close(args.ready_fd)
        else:
            print(f"CONTROLLER_ADDRESS={ctl.server.address}", flush=True)
        await ctl.wait_shutdown()

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
