"""Task specifications — the unit shipped from submitter to executor.

Role-equivalent to the reference's TaskSpecification (ref:
src/ray/common/task/task_spec.h, common.proto TaskSpec).  A spec carries the
function (by content-hash into the cluster function table, so hot loops
don't reship code), argument slots (inline value or object reference),
resource demand, retry policy, and scheduling strategy.
"""

from __future__ import annotations

import enum
import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID
from .resources import ResourceSet


class TaskKind(enum.Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


class ArgKind(enum.Enum):
    VALUE = 0      # inline serialized value
    OBJECT_REF = 1  # must be resolved before dispatch


@dataclass
class TaskArg:
    kind: ArgKind
    value: Any = None                  # for VALUE (already picklable payload)
    object_id: Optional[ObjectID] = None  # for OBJECT_REF


@dataclass
class SchedulingStrategy:
    """Where a task may run.

    Covers the reference's strategy set (ref:
    python/ray/util/scheduling_strategies.py): default hybrid, SPREAD,
    node-affinity, and placement-group bundles.
    """

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Optional[NodeID] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    kind: TaskKind
    func_id: str                       # sha256 hex of the function blob
    func_blob: Optional[bytes] = None  # present on first submission
    method_name: str = ""              # for ACTOR_TASK
    args: List[TaskArg] = field(default_factory=list)
    kwargs_keys: List[str] = field(default_factory=list)  # trailing args are kwargs
    num_returns: int = 1
    resources: ResourceSet = field(default_factory=ResourceSet)
    max_retries: int = 0
    retry_exceptions: bool = False
    # Execution attempt number, bumped by the owner's retry loop and
    # carried in task events so a retry's RUNNING can supersede the
    # previous attempt's FAILED headline state regardless of which
    # host's clock stamped which event.
    sched_attempt: int = 0
    name: str = ""
    scheduling: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: Optional[Dict[str, Any]] = None
    # Actor-specific.
    actor_id: Optional[ActorID] = None
    max_restarts: int = 0
    max_concurrency: int = 1
    # Named concurrency groups (ref: concurrency_group_manager.h:34):
    # creation specs carry {group: capacity}; actor-task specs carry
    # the explicit per-call group override ("" = the method's default
    # group, resolved executor-side).
    concurrency_groups: Dict[str, int] = field(default_factory=dict)
    concurrency_group: str = ""
    # Creation specs: per-method defaults from @ray_tpu.method
    # ({name: {"concurrency_group": ..., "num_returns": ...}}), so
    # handles reconstructed by name lookup keep them.
    method_options: Dict[str, Dict[str, Any]] = field(
        default_factory=dict)
    # Actor-task specs: True when the actor executes per concurrency
    # group — submission must not serialize calls (a dedicated signal;
    # max_concurrency stays the actor's honest value).
    unordered: bool = False
    actor_name: str = ""               # named actor registration
    namespace: str = ""
    seq_no: int = 0                    # per-actor submission order
    method_names: List[str] = field(default_factory=list)  # actor methods
    lifetime: Optional[str] = None     # None | "detached"
    # Lineage: owner address is attached by the submitting worker.
    owner_hint: str = ""
    # Tracing: submitter's span context (ref: tracing_helper.py:88
    # span injection through submission); None when tracing is off.
    trace_ctx: Optional[Dict[str, str]] = None
    # Hot-path introspection: preallocated perf_counter stamp slots
    # (util/hotpath.py slot layout) on the sampled 1-in-N task; None
    # for the unsampled fast path.
    hp: Optional[List[float]] = None

    # num_returns sentinel for streaming generators (ref:
    # num_returns="streaming" / ObjectRefGenerator, _raylet.pyx:284):
    # the executor reports yielded items incrementally; return ids are
    # minted per yield as for_task_return(task_id, index).
    STREAMING: int = -1

    @property
    def is_streaming(self) -> bool:
        return self.num_returns == TaskSpec.STREAMING

    def return_object_ids(self) -> List[ObjectID]:
        if self.is_streaming:
            # The index-0 sentinel anchors submission bookkeeping
            # (pending set, cancel routing); item ids start at 1.
            return [ObjectID.for_task_return(self.task_id, 0)]
        return [
            ObjectID.for_task_return(self.task_id, i + 1)
            for i in range(self.num_returns)
        ]

    def display_name(self) -> str:
        if self.name:
            return self.name
        if self.kind == TaskKind.ACTOR_TASK:
            return f"actor.{self.method_name}"
        return self.func_id[:8]


def func_id_of(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


@dataclass
class TaskResult:
    """Executor -> owner report for one finished task."""

    task_id: TaskID
    ok: bool
    # Per-return: ("inline", payload_bytes) or ("store", object_id) entries.
    returns: List[Tuple[str, Any]] = field(default_factory=list)
    error: Optional[Any] = None  # serialized exception (TaskError)
    worker_log: str = ""
    # ObjectRef ids embedded in inline return payloads; the executor holds
    # a transit borrow on each until the owner confirms receipt (ownership
    # handoff, ref: reference_count.h borrowed-refs protocol).
    transit_refs: List[ObjectID] = field(default_factory=list)
    # Streaming tasks: how many items were yielded before completion
    # (items themselves travel as stream_item notifies).
    streamed: int = 0
    # True: the worker returned the task UNEXECUTED (its current task
    # blocked in get(), so queued work must fail over to another
    # worker instead of deadlocking behind it) — the owner re-enqueues.
    requeue: bool = False
    # Hot-path introspection: the sampled spec's stamp vector echoed
    # back with the worker-side slots filled (util/hotpath.py).
    hp: Optional[List[float]] = None
