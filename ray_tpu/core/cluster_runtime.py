"""ClusterRuntime — the in-process core of every driver and worker.

Role-equivalent to the reference's CoreWorker (ref:
src/ray/core_worker/core_worker.h:166 with SubmitTask at
core_worker.cc:2484, NormalTaskSubmitter transport/normal_task_submitter.h:74,
ActorTaskSubmitter transport/actor_task_submitter.h:75): owns the
per-process memory store, resolves dependencies, leases workers from the
node agent, pushes tasks directly to leased workers, and routes actor
calls straight to the actor's worker process.  All IO runs on a dedicated
event-loop thread so user threads only ever block on local events.

Head-node bring-up (controller + agent subprocesses) mirrors
python/ray/_private/node.py:1407 start_head_processes.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Set, Tuple

from .config import RuntimeConfig
from .errors import (ActorDiedError, ActorError, GetTimeoutError,
                     ObjectLostError, TaskCancelledError, TaskError,
                     WorkerCrashedError)
from .ids import ActorID, JobID, NodeID, ObjectID
from .object_store import MemoryStore, SharedObjectStore
from .object_ref import ObjectRef
from .rpc import EventLoopThread, RemoteCallError, RpcClient, RpcError
from .runtime import BaseRuntime
from .task import ArgKind, TaskArg, TaskKind, TaskResult, TaskSpec

logger = logging.getLogger("ray_tpu.runtime")

_PUSH_RETRY_STATES = ("PENDING", "RESTARTING")


class _StoreRef:
    """Memory-store descriptor for a value living in the object plane."""

    __slots__ = ("size", "node_hint")

    def __init__(self, size: int, node_hint: str = ""):
        self.size = size
        self.node_hint = node_hint


class _Submission:
    """Owner-side in-flight record for one normal task, for cancel().

    Tracks where the lease request currently waits (agent_addr +
    request_id while queued) and where the task runs once pushed
    (worker_addr/worker_id), so cancellation can be routed (ref:
    core_worker.cc CancelTask / node_manager CancelWorkerLease).
    """

    __slots__ = ("spec", "request_id", "cancelled", "force", "agent_addr",
                 "worker_addr", "worker_id", "pushed", "done",
                 "cancel_event")

    def __init__(self, spec):
        self.spec = spec
        self.request_id = uuid.uuid4().hex
        self.cancelled = False
        self.force = False
        self.agent_addr: Optional[str] = None
        self.worker_addr: Optional[str] = None
        self.worker_id = None
        self.pushed = False
        self.done = False
        # Interrupts dep-resolution waits; set on the io loop by cancel().
        self.cancel_event = asyncio.Event()


class _CancelledInFlight(Exception):
    """Internal: submission observed its cancel flag mid-flight."""


class _StreamState:
    """Owner-side state of one streaming-generator task (ref: the
    owner half of ObjectRefGenerator, _raylet.pyx:284): item refs
    arrive as stream_item notifies and queue here until the consumer
    nexts them; `done` latches on the final TaskResult."""

    __slots__ = ("ready", "produced", "consumed", "done", "error",
                 "total", "event", "lock", "worker_addr",
                 "error_delivered")

    def __init__(self):
        import collections
        import threading

        self.ready = collections.deque()   # ObjectIDs in yield order
        self.produced = 0
        self.consumed = 0
        self.done = False
        self.error: Optional[Any] = None
        self.total: Optional[int] = None
        self.event = threading.Event()
        self.lock = threading.Lock()
        self.worker_addr: Optional[str] = None
        self.error_delivered = False


class _PooledLease:
    """A granted worker lease cached by the owner for task reuse (ref:
    normal_task_submitter.h:74 — the submitter caches leased workers
    and pipelines same-shaped tasks onto them instead of paying a
    lease round-trip per task).  At most ONE task runs on a pooled
    lease at a time (matching OnWorkerIdle semantics), so queued tasks
    can never deadlock behind a blocked task on the same worker."""

    __slots__ = ("lease_id", "agent_addr", "worker_addr", "worker_id",
                 "chip_ids", "idle_since", "dead", "inflight")

    def __init__(self, lease_id, agent_addr, worker_addr, worker_id,
                 chip_ids):
        self.lease_id = lease_id
        self.agent_addr = agent_addr
        self.worker_addr = worker_addr
        self.worker_id = worker_id
        self.chip_ids = chip_ids
        self.idle_since = 0.0
        self.dead = False
        # Pushes currently in flight on this lease (reported to the
        # agent so `rt list leases` can show pipeline depth).
        self.inflight = 0


class _SchedKeyState:
    """Owner-side per-scheduling-key submission state: a FIFO of tasks
    waiting for a worker, the pool of granted leases, and the set of
    in-flight lease requests (ref: SchedulingKey entries in
    normal_task_submitter.h — one task queue + worker set + pending
    lease request per (resource shape, runtime env) class)."""

    __slots__ = ("key", "base_payload", "queue", "leases", "idle",
                 "request_agents", "repump_scheduled")

    def __init__(self, key, base_payload):
        self.key = key
        self.base_payload = base_payload
        from collections import deque

        # (spec, _Submission, future-of-TaskResult) triples.
        self.queue = deque()
        self.leases: Dict[int, _PooledLease] = {}
        self.idle: List[_PooledLease] = []
        # request_id -> agent address currently holding that request.
        self.request_agents: Dict[str, str] = {}
        self.repump_scheduled = False


class ClusterRuntime(BaseRuntime):
    def __init__(self, config: RuntimeConfig, *,
                 address: Optional[str] = None,
                 num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 custom_resources: Optional[Dict[str, float]] = None,
                 namespace: str = "",
                 # Worker-role wiring (set by worker_main):
                 _connect: Optional[Dict[str, str]] = None,
                 _job_id: Optional[JobID] = None):
        self._procs: List[subprocess.Popen] = []
        self._owns_head = False
        self.namespace = namespace
        self.is_worker = _connect is not None
        if _connect is not None:
            self.session = _connect["session"]
            self.controller_addr = _connect["controller"]
            self.agent_addr = _connect["agent"]
        elif address is not None:
            self.session, self.controller_addr, self.agent_addr = \
                self._connect_existing(config, address, num_cpus, num_tpus,
                                       custom_resources)
        else:
            self.session, self.controller_addr, self.agent_addr = \
                self._start_head(config, num_cpus, num_tpus,
                                 custom_resources)
            self._owns_head = True
        self.io = EventLoopThread("rt-io")
        from .object_store import create_store

        self.store = create_store(self.session, config)
        if hasattr(self.store, "on_pressure"):
            # Pool backend: a full slab asks the agent to evict/spill
            # (make_room) instead of failing the seal.
            self.store.on_pressure = self._request_store_room
        self.memory = MemoryStore()
        self._runtime_id = uuid.uuid4().hex[:16]
        self._ctl: Optional[RpcClient] = None
        self._agent: Optional[RpcClient] = None
        self._worker_clients: Dict[str, RpcClient] = {}
        self._actor_cache: Dict[ActorID, Dict] = {}
        # Batched actor registration: unnamed actor registrations
        # coalesce on a 5 ms window into one bulk register_actors RPC
        # (a 100-replica fan-out = a handful of controller round
        # trips).  Io-loop state only.
        self._actor_reg_buf: List = []
        self._actor_reg_flusher = None
        # Actors whose batched registration has not committed at the
        # controller yet: the submit path must wait these out before
        # polling get_actor, or a fast first call would read "unknown
        # actor" in the 5 ms window.  Marked on the caller's thread in
        # create_actor (program order guarantees the mark exists
        # before any call on the handle), cleared on the io loop.
        self._actor_reg_pending: Dict[ActorID, bool] = {}
        self._pending_returns: Set[ObjectID] = set()
        self._submissions: Dict[ObjectID, _Submission] = {}
        self._completion_events: Dict[ObjectID, asyncio.Event] = {}
        # RLock: taken on the ObjectRef.__del__ path (remove_local_ref),
        # which cyclic GC can fire on a thread already inside it.
        self._pending_lock = threading.RLock()
        # -- Distributed reference counting state (ref:
        # reference_count.h:66, redesigned: each process reports only its
        # 0<->1 holder transitions to the centralized controller
        # directory).  RLock: remove_local_ref runs from ObjectRef.__del__,
        # which GC may fire while this thread already holds the lock.
        self._refs_lock = threading.RLock()
        self._local_ref_counts: Dict[ObjectID, int] = {}
        self._submitted_holds: Dict[ObjectID, int] = {}  # in-flight args
        self._owned_ids: Set[ObjectID] = set()      # ids created here
        self._owned_plane: Set[ObjectID] = set()    # owned + in the plane
        self._escaped_refs: Set[ObjectID] = set()   # may have borrowers
        self._local_puts: Set[ObjectID] = set()     # put()s w/o embedded
        self._bg_ops: List = []                     # coalesced loop work
        self._bg_scheduled = False
        # RLock: _bg_submit is reachable from ObjectRef.__del__, and a
        # GC run triggered by an allocation under the lock (the drain
        # loop's list() copy) can re-enter on the same thread.
        self._bg_lock = threading.RLock()
        # Owned in-band refs that were pickled OUT of this process while
        # still pending: their values must be written through to the
        # object plane on completion (see promote_refs_to_plane).
        self._escaped: Set[ObjectID] = set()
        self._borrows_registered: Set[ObjectID] = set()
        self._free_on_complete: Set[ObjectID] = set()
        # Lineage: creation specs of owned plane objects, replayed when
        # every copy is lost (ref: object_recovery_manager.h:38).
        self._lineage: Dict[ObjectID, TaskSpec] = {}
        self._reconstructing: Dict[ObjectID, asyncio.Future] = {}
        self._actor_submit_locks: Dict[ActorID, asyncio.Lock] = {}
        # Lease pool (ref: normal_task_submitter.h scheduling_key_entries_):
        # all state touched only on the io loop thread.
        self._sched_states: Dict[tuple, _SchedKeyState] = {}
        self._lease_sweeper: Optional[asyncio.Task] = None
        self._streams: Dict[str, _StreamState] = {}
        self._submit_buf: List[tuple] = []
        self._submit_buf_lock = threading.Lock()
        # Batched-exec channel: reply_id -> (status_fut, st, pl, item).
        self._reply_counter = itertools.count(1)
        self._reply_waiters: Dict[int, tuple] = {}
        self._shutdown_flag = False
        self._event_cursor = 0
        # Owner-side scheduling-transition events (queued ->
        # lease_requested -> pipelined/granted -> requeued), flushed
        # to the controller's task-event sink so `rt explain` can show
        # WHY a task landed where it did.  Buffer is bounded; a
        # submission storm drops oldest explainability events rather
        # than growing without limit.
        self._sched_ev_buf: List[Dict] = []
        self._sched_ev_lock = threading.Lock()
        self._sched_ev_dropped = 0
        self._sched_flusher_started = False
        # Hot-path introspection: completed phase records (sampled
        # tasks only) ride the same 0.5s task_events flush — zero
        # extra wakeups or RPCs on the submission path.
        self._hotpath_buf: List[Dict] = []
        # Actor replies awaiting redelivery across an owner reconnect
        # (reply_id set; guards double-spawn on repeated disconnects).
        self._redelivering: Set[int] = set()
        # Worker-role: current lease for blocked-CPU accounting.
        self.current_lease_id: Optional[int] = None
        self.io.run(self._async_init())
        job_id = _job_id
        self._registered_job_int: Optional[int] = None
        if job_id is None:
            r = self.io.run(self._ctl.call("register_job", {
                "driver": f"pid-{os.getpid()}",
                # Multi-tenant link: a submitted job's entrypoint
                # driver carries its submission id so leases/PGs
                # tagged with this internal job resolve to the tenant
                # for quota enforcement and goodput attribution.
                "tenant": os.environ.get("RT_JOB_ID", "")}))
            job_id = JobID.from_int(r["job_id"])
            self._registered_job_int = r["job_id"]
        super().__init__(config, job_id)
        if not self.is_worker:
            self.io.spawn(self._event_poll_loop())

    # ----------------------------------------------------------- bring-up
    @staticmethod
    def _session_name() -> str:
        return f"{int(time.time())}_{os.getpid()}"

    def _start_head(self, config, num_cpus, num_tpus, custom):
        from . import node_launcher

        session = self._session_name()
        proc, controller_addr = node_launcher.start_controller(
            config, session, driver_pid=os.getpid())
        self._procs.append(proc)
        proc, agent_addr, _nid = node_launcher.start_node_agent(
            config, session, controller_addr, num_cpus=num_cpus,
            num_tpus=num_tpus, custom_resources=custom, is_head=True,
            tag="head")
        self._procs.append(proc)
        return session, controller_addr, agent_addr

    def _connect_existing(self, config, address, num_cpus, num_tpus, custom):
        """Driver connecting to a running cluster; needs a colocated agent.
        Starts one if this host has none (matching ray.init(address=...)
        semantics where the driver machine must run a raylet)."""
        probe = EventLoopThread("rt-probe")
        try:
            cli = RpcClient(address, connect_timeout=10.0)
            info = probe.run(self._probe(cli))
            session = info["session"]
            nodes = info["nodes"]
            from .net import host_of, is_local_address

            agent_addr = None
            for n in nodes:
                if n["alive"] and is_local_address(
                        host_of(n["agent_addr"])):
                    agent_addr = n["agent_addr"]
                    break
            if agent_addr is None:
                raise RuntimeError("no local node agent found to attach to")
            return session, address, agent_addr
        finally:
            probe.stop()

    @staticmethod
    async def _probe(cli: RpcClient):
        pong = await cli.call("ping")
        nodes = await cli.call("list_nodes", {})
        await cli.close()
        return {"session": pong["session"], "nodes": nodes}

    async def _async_init(self):
        self._ctl = RpcClient(self.controller_addr,
                              tag=f"rt-{os.getpid()}")
        await self._ctl.connect()
        self._agent = RpcClient(self.agent_addr, tag=f"rt-{os.getpid()}")
        await self._agent.connect()
        # Direct-write channel for per-object control notifies (see
        # NotifySideChannel): connected lazily on first notify, but
        # never DIALED from the io-loop thread (a GC-triggered release
        # there must not block the loop on a connect).
        from .rpc import NotifySideChannel

        io_thread = threading.current_thread()  # we're on the io loop
        self._side_channel = NotifySideChannel(
            self.agent_addr,
            avoid_dial=lambda: threading.current_thread() is io_thread)

    # ------------------------------------------------------------- helpers
    def _completion_event(self, oid: ObjectID) -> asyncio.Event:
        ev = self._completion_events.get(oid)
        if ev is None:
            ev = self._completion_events[oid] = asyncio.Event()
        return ev

    def _mark_pending(self, oids: List[ObjectID]) -> None:
        with self._pending_lock:
            self._pending_returns.update(oids)
        with self._refs_lock:
            self._owned_ids.update(oids)

    # ------------------------------------------ in-band -> plane promotion
    def promote_refs_to_plane(self, oids) -> None:
        """Write owned MEMORY-STORE-ONLY values through to the object
        plane when their refs escape this process — pickled into task
        args, a put payload, or a return value (ref: core_worker
        promoting inlined small objects to plasma once their ObjectRef
        is borrowed).  Without this, another process that receives such
        a ref polls the object directory forever: the value exists only
        in our address space.  Still-pending refs are remembered and
        promoted when their result arrives (_accept_returns)."""
        for oid in oids:
            # Order matters (TOCTOU): set the promotion promise FIRST,
            # then look for the value.  Whichever side sees both the
            # value and the promise does the write-through — a result
            # landing between our steps is promoted by the completion
            # path (which stores the value before reading _escaped
            # under the same lock).  Double promotion is idempotent.
            with self._refs_lock:
                if oid not in self._owned_ids or \
                        oid in self._owned_plane:
                    continue
                self._escaped.add(oid)
            ok, val = self.memory.get_nowait(oid)
            if not ok:
                continue  # pending: completion path fulfils the promise
            with self._refs_lock:
                self._escaped.discard(oid)
            if isinstance(val, (_StoreRef, TaskError)):
                continue
            self._write_through(oid, val)

    def _write_through(self, oid: ObjectID, val: Any) -> None:
        try:
            size = self.store.create_and_seal(oid, val)
        except Exception:
            logger.warning("in-band promotion of %s failed",
                           oid.hex()[:12], exc_info=True)
            return
        with self._refs_lock:
            self._owned_plane.add(oid)
        from .rpc import spawn_task

        async def _register():
            try:
                await self._agent.call("register_object",
                                       {"object_id": oid, "size": size})
            except Exception:
                pass  # consumers keep polling; next heartbeat re-syncs

        # Fire-and-forget: callers may already be ON the io loop
        # (completion path), so never block on it here.
        self.io.call_soon(lambda: spawn_task(_register(), self.io.loop))

    @staticmethod
    def _scan_embedded_refs(values) -> List[ObjectID]:
        """Ids of ObjectRefs nested anywhere inside ``values`` (one
        cloudpickle pass with the ref collector active)."""
        import cloudpickle

        from .object_ref import collect_embedded_refs

        interesting = [v for v in values
                       if not isinstance(v, (int, float, str, bytes,
                                             bool, type(None)))]
        if not interesting:
            return []
        with collect_embedded_refs() as found:
            try:
                # buffer_callback keeps large binary payloads (numpy
                # etc.) out-of-band and UNCOPIED — this pass only needs
                # the ref collector side effect, not the bytes.
                cloudpickle.dumps(interesting, protocol=5,
                                  buffer_callback=lambda _b: None)
            except Exception:
                return []
        return list(found)

    def _store_result_value(self, oid: ObjectID, value: Any) -> None:
        self.memory.put(oid, value)
        with self._refs_lock:
            escaped = oid in self._escaped
            self._escaped.discard(oid)
        if escaped and not isinstance(value, (_StoreRef, TaskError)):
            # A ref to this (then-pending) value left the process;
            # fulfil the promotion promise now that the value exists.
            # Off-loop: this path runs on the io loop and the seal can
            # ride store backpressure.
            loop = self.io.loop
            self.io.call_soon(
                lambda: loop.run_in_executor(None, self._write_through,
                                             oid, value))
        with self._pending_lock:
            self._pending_returns.discard(oid)
        ev = self._completion_events.get(oid)
        if ev is not None:
            ev.set()
        with self._refs_lock:
            free_now = (oid in self._free_on_complete
                        and self._local_ref_counts.get(oid, 0) == 0
                        and self._submitted_holds.get(oid, 0) == 0)
            self._free_on_complete.discard(oid)
        if free_now:
            self._release_object(oid)

    # --------------------------------------------- reference counting hooks
    def add_local_ref(self, object_id: ObjectID) -> None:
        with self._refs_lock:
            n = self._local_ref_counts.get(object_id, 0)
            self._local_ref_counts[object_id] = n + 1
            if n > 0 or object_id in self._owned_ids \
                    or object_id in self._borrows_registered \
                    or self._shutdown_flag:
                return
            # First local ref to a foreign object: we are a borrower —
            # tell the directory so the owner's release can't free it
            # from under us.
            self._borrows_registered.add(object_id)
        self._notify_async("add_borrower", {
            "object_id": object_id, "holder": self._runtime_id})

    def remove_local_ref(self, object_id: ObjectID) -> None:
        if self._shutdown_flag:
            return
        with self._refs_lock:
            n = self._local_ref_counts.get(object_id, 0) - 1
            if n > 0:
                self._local_ref_counts[object_id] = n
                return
            self._local_ref_counts.pop(object_id, None)
            if n < 0:  # ref born under a previous runtime in this process
                return
            if self._submitted_holds.get(object_id, 0) > 0:
                return  # release happens when the in-flight task finishes
            with self._pending_lock:
                if object_id in self._pending_returns:
                    # Fire-and-forget: the producing task still runs; the
                    # result is freed when it lands.
                    self._free_on_complete.add(object_id)
                    return
        self._release_object(object_id)

    def mark_ref_escaped(self, oid: ObjectID) -> None:
        """This ref left the process (pickled, or passed as a task
        arg): another process may register a borrow, so the eager
        local free in _release_object is off for it — only the
        controller-driven release (which waits out borrowers) may
        delete the primary copy."""
        with self._refs_lock:
            self._escaped_refs.add(oid)

    def _add_submitted_holds(self, oids: List[ObjectID]) -> None:
        """Pin args of an in-flight task (ref: reference_count.h
        submitted_task_ref_count) — `f.remote(g.remote())` drops the inner
        ref right after submission; the hold keeps the object alive until
        the consuming task completes."""
        with self._refs_lock:
            for oid in oids:
                self._escaped_refs.add(oid)
                self._submitted_holds[oid] = \
                    self._submitted_holds.get(oid, 0) + 1

    def _release_submitted_holds(self, oids: List[ObjectID]) -> None:
        for oid in oids:
            with self._refs_lock:
                n = self._submitted_holds.get(oid, 0) - 1
                if n > 0:
                    self._submitted_holds[oid] = n
                    continue
                self._submitted_holds.pop(oid, None)
                if self._local_ref_counts.get(oid, 0) > 0:
                    continue
                with self._pending_lock:
                    if oid in self._pending_returns:
                        self._free_on_complete.add(oid)
                        continue
            self._release_object(oid)

    def _release_object(self, oid: ObjectID) -> None:
        """All local holders are gone: drop the value and tell the
        directory (owner release or borrow removal)."""
        self.memory.delete(oid)
        with self._refs_lock:
            owned = oid in self._owned_ids
            self._owned_ids.discard(oid)
            plane = oid in self._owned_plane
            self._owned_plane.discard(oid)
            self._lineage.pop(oid, None)
            borrowed = oid in self._borrows_registered
            self._borrows_registered.discard(oid)
            escaped = oid in self._escaped_refs
            self._escaped_refs.discard(oid)
            local_put = oid in self._local_puts
            self._local_puts.discard(oid)
        if owned and plane:
            if not escaped:
                # Eager local free (ref: plasma's out-of-scope delete):
                # the ref never left this process, so no borrower can
                # exist — free the store bytes NOW so the allocator
                # reuses the (hot) block, instead of waiting out the
                # release round trip through the controller.  The
                # directory entry still retires below; the store
                # delete there becomes a no-op.
                try:
                    self.store.delete(oid)
                except Exception:
                    pass
            if self._shutdown_flag:
                return  # teardown owns cleanup; don't re-dial anything
            if local_put and not escaped:
                # Fast release: one NOTIFY to the local agent retires
                # the directory entry + published locations — no
                # controller owner_release/free_object round trip (no
                # borrowers or induced borrows can exist for a
                # never-pickled plain put).  Same-channel FIFO keeps
                # it behind the object's own registration.
                if self._side_channel.notify(
                        "owner_release_local", {"object_id": oid}):
                    return

                def _fast_release():
                    try:
                        self._agent.notify_nowait(
                            "owner_release_local", {"object_id": oid})
                    except Exception:
                        pass  # agent gone: node (and copy) is dying

                self._bg_submit(_fast_release)
            else:
                self._notify_async("owner_release", {"object_id": oid})
        elif borrowed:
            self._notify_async("remove_borrower", {
                "object_id": oid, "holder": self._runtime_id})

    def _bg_submit(self, fn) -> None:
        """Run ``fn`` on the event-loop thread, coalescing wakeups: a
        burst of background ops (register/release per put in a tight
        loop) pays ONE cross-thread self-pipe write while the loop is
        still draining, not one per op — the wakeup send contends on
        the GIL with the loop thread and was costing more than the ops
        themselves.  FIFO order is preserved, so a register queued
        before a release is written first."""
        with self._bg_lock:
            self._bg_ops.append(fn)
            if self._bg_scheduled:
                return
            self._bg_scheduled = True
        try:
            self.io.call_soon(self._bg_drain)
        except Exception:
            # Loop stopped (shutdown race): drop the ops — matching
            # the old fire-and-forget behavior — and unlatch so a
            # later submit doesn't silently no-op forever.
            with self._bg_lock:
                self._bg_ops.clear()
                self._bg_scheduled = False

    def _bg_drain(self) -> None:
        while True:
            with self._bg_lock:
                if not self._bg_ops:
                    self._bg_scheduled = False
                    return
                # Swap, don't copy+clear: a GC-triggered re-entrant
                # submit landing mid-copy would be wiped by clear().
                ops, self._bg_ops = self._bg_ops, []
            for fn in ops:
                try:
                    fn()
                except Exception:
                    pass

    def _notify_async(self, method: str, payload: Dict) -> None:
        """Fire-and-forget controller notification from any thread
        (including GC running __del__); must never block or raise."""
        if self._shutdown_flag:
            return
        try:
            self._bg_submit(lambda: self.io.loop.create_task(
                self._notify_ignore_errors(method, payload)))
        except Exception:
            pass

    async def _notify_ignore_errors(self, method: str,
                                    payload: Dict) -> None:
        try:
            await self._ctl.call(method, payload)
        except (RpcError, RemoteCallError, asyncio.CancelledError):
            pass

    @property
    def caller_tag(self) -> str:
        """Tag this runtime registers on worker connections; workers
        notify stream items back to it."""
        return f"owner-{self._runtime_id}"

    # ----------------------------------------- scheduler explainability
    def _sched_event(self, spec: TaskSpec, state: str,
                     **detail) -> None:
        """Record one owner-side scheduling transition with reason
        tags (ref: the task-state machine in gcs_task_manager — here
        extended with the owner's lease-pool decisions, which the
        reference leaves invisible).  Any thread; never raises."""
        try:
            ev = {"task_id": spec.task_id.hex(), "state": state,
                  "ts": time.time(), "name": spec.display_name(),
                  "kind": spec.kind.name,
                  "attempt": getattr(spec, "sched_attempt", 0)}
            if detail:
                ev["detail"] = {k: v for k, v in detail.items()
                                if v is not None}
            with self._sched_ev_lock:
                self._sched_ev_buf.append(ev)
                if len(self._sched_ev_buf) > 10000:
                    # Counted, not silent: the drop tally rides the
                    # next flush into the controller's
                    # task_events_dropped so a gapped `rt explain`
                    # chain is attributable to backpressure.
                    self._sched_ev_dropped += 5000
                    del self._sched_ev_buf[:5000]
                start = not self._sched_flusher_started
                if start:
                    self._sched_flusher_started = True
            if start:
                from .rpc import spawn_task

                self.io.call_soon(
                    lambda: spawn_task(self._sched_event_flush_loop(),
                                       self.io.loop))
        except Exception:
            pass

    async def _sched_event_flush_loop(self) -> None:
        while not self._shutdown_flag:
            await asyncio.sleep(0.5)
            with self._sched_ev_lock:
                batch, self._sched_ev_buf = self._sched_ev_buf, []
                dropped, self._sched_ev_dropped = \
                    self._sched_ev_dropped, 0
                hp_batch, self._hotpath_buf = self._hotpath_buf, []
            if not batch and not dropped and not hp_batch:
                continue
            payload = {"events": batch, "dropped": dropped}
            if hp_batch:
                payload["hotpath"] = hp_batch
                payload["source"] = self.caller_tag
            try:
                await self._ctl.call("task_events", payload)
            except (RpcError, RemoteCallError,
                    asyncio.CancelledError):
                # Explainability is best-effort, but keep the drop
                # tally for the next successful flush.  (Hot-path
                # records are sampled observability — dropped.)
                with self._sched_ev_lock:
                    self._sched_ev_dropped += dropped

    def _hotpath_record(self, spec: TaskSpec, hp: List[float]) -> None:
        """Io loop: stamp OWNER_DONE, fold the vector into a phase
        record, and buffer it for the task_events flush tick.  Never
        raises — this sits on the result-accept path."""
        try:
            from ..util import hotpath as _hotpath

            hp[_hotpath.OWNER_DONE] = time.perf_counter()
            rec = _hotpath.record_from_stamps(hp, spec.display_name())
            if rec is None:
                return
            with self._sched_ev_lock:
                self._hotpath_buf.append(rec)
                if len(self._hotpath_buf) > 4096:
                    del self._hotpath_buf[:2048]
                start = not self._sched_flusher_started
                if start:
                    self._sched_flusher_started = True
            if start:
                from .rpc import spawn_task

                self.io.call_soon(
                    lambda: spawn_task(self._sched_event_flush_loop(),
                                       self.io.loop))
        except Exception:
            pass

    async def _worker_client(self, addr: str) -> RpcClient:
        cli = self._worker_clients.get(addr)
        if cli is None or not cli.connected:
            cli = RpcClient(addr, tag=self.caller_tag,
                            connect_timeout=10.0)
            cli.on_notify("stream_item", self._on_stream_item)
            cli.on_notify("task_results", self._on_task_results)
            cli.on_disconnect(
                lambda a=addr: self._on_worker_disconnect(a))
            await cli.connect()
            self._worker_clients[addr] = cli
        return cli

    # ---------------------------------------------- streaming generators
    def _on_stream_item(self, p: Dict) -> None:
        """Io-loop: a generator task yielded item ``index`` (ref:
        the owner-side report handling behind ObjectRefGenerator)."""
        st = self._streams.get(p["task_id"].hex())
        if st is None:
            return
        oid = p["object_id"]
        kind, data = p["entry"]
        with self._refs_lock:
            self._owned_ids.add(oid)
            if kind != "inline":
                self._owned_plane.add(oid)
        if kind == "inline":
            from . import serialization

            self.memory.put(oid, serialization.unpack(data))
        else:
            size, node_hint = data
            self.memory.put(oid, _StoreRef(size, node_hint))
        with st.lock:
            st.ready.append(oid)
            st.produced = max(st.produced, p["index"])
        st.event.set()

    def _finalize_stream(self, spec: TaskSpec,
                         result: Optional[TaskResult],
                         error: Optional[Any] = None) -> None:
        st = self._streams.get(spec.task_id.hex())
        sentinel = spec.return_object_ids()[0]
        sub = self._submissions.pop(sentinel, None)
        if sub is not None:
            sub.done = True
        if st is not None:
            with st.lock:
                st.done = True
                if result is not None and result.ok:
                    st.total = result.streamed
                else:
                    st.error = (result.error if result is not None
                                else error)
            st.event.set()
        self._store_result_value(sentinel, None)
        if result is not None:
            for emb in result.transit_refs or []:
                self._notify_async("remove_borrower", {
                    "object_id": emb,
                    "holder": f"transit:{spec.task_id.hex()}"})

    def _stream_put_error(self, oid: ObjectID, err: Any) -> None:
        with self._refs_lock:
            self._owned_ids.add(oid)
        self.memory.put(oid, err)

    def _stream_close(self, task_id) -> None:
        """Drop a stream's owner-side state (consumer exhausted or
        abandoned it); a still-running producer gets a best-effort
        cancel so its backpressure wait can't spin forever."""
        st = self._streams.pop(task_id.hex(), None)
        if st is None or st.done:
            return
        from .ids import ObjectID as _OID

        sentinel = _OID.for_task_return(task_id, 0)
        sub = self._submissions.get(sentinel)
        if sub is not None and not sub.done:
            sub.cancelled = True
            self.io.call_soon(sub.cancel_event.set)
            try:
                self.io.run(self._cancel_inflight(sub), timeout=5.0)
            except Exception:
                pass

    def stream_ack(self, task_id, consumed: int,
                   worker_addr: Optional[str]) -> None:
        """Generator consumer thread: release executor backpressure."""
        if worker_addr is None:
            return

        async def _send():
            try:
                cli = await self._worker_client(worker_addr)
                await cli.notify("stream_ack", {
                    "task_id": task_id, "consumed": consumed})
            except (RpcError, OSError):
                pass  # worker gone; the final result surfaces it

        from .rpc import spawn_task

        self.io.call_soon(lambda: spawn_task(_send(), self.io.loop))

    async def _event_poll_loop(self):
        """Long-poll controller pubsub to invalidate actor caches and
        stream this job's worker logs to the console (ref:
        src/ray/pubsub long-poll subscriber + log_monitor.py driver
        streaming)."""
        channels = ["actor", "node"]
        stream_logs = getattr(self.config, "log_to_driver", True)
        if stream_logs:
            channels.append("worker_logs")
        while not self._shutdown_flag:
            try:
                r = await self._ctl.call("poll_events", {
                    "cursor": self._event_cursor,
                    "channels": channels, "timeout": 10.0},
                    timeout=15.0)
            except (RpcError, asyncio.TimeoutError, RemoteCallError):
                await asyncio.sleep(0.5)
                continue
            self._event_cursor = r.get("cursor", self._event_cursor)
            if r.get("cursor_expired"):
                # Events were trimmed past our cursor: cached actor states
                # may silently be stale (a missed DEAD would route calls to
                # a gone address forever).  Full resync: drop the cache so
                # the next _actor_info falls through to the controller.
                self._actor_cache.clear()
                continue
            for _seq, ch, data in r.get("events", []):
                if ch == "actor":
                    aid = data["actor_id"]
                    cached = self._actor_cache.get(aid)
                    if cached is not None:
                        cached["state"] = data["state"]
                        cached["worker_addr"] = data.get("worker_addr", "")
                elif ch == "worker_logs" and stream_logs:
                    self._print_worker_logs(data)

    def _print_worker_logs(self, rec) -> None:
        """Print a worker-log batch belonging to THIS job, tagged like
        the reference's ``(pid=..., ip=...)`` prefix."""
        if rec.get("job_id") != self.job_id.hex():
            return
        prefix = (f"({rec.get('pid')}, "
                  f"node={str(rec.get('node_id', ''))[:8]}) ")
        out = "".join(prefix + line + "\n"
                      for line in rec.get("lines", []))
        if out:
            sys.stdout.write(out)
            sys.stdout.flush()

    # ------------------------------------------------- dependency resolution
    async def _resolve_deps(self, spec: TaskSpec,
                            sub: Optional[_Submission] = None) -> None:
        """Owner-side resolution (ref: dependency_resolver.h): wait for
        owned pending refs; inline small owned values; leave plane refs for
        the executor to pull.  A cancelled submission interrupts the wait
        — otherwise cancel() on a dep-blocked task would hang forever."""
        for arg in spec.args:
            if arg.kind != ArgKind.OBJECT_REF:
                continue
            oid = arg.object_id
            with self._pending_lock:
                pending = oid in self._pending_returns
            if pending:
                waiters = [asyncio.ensure_future(
                    self._completion_event(oid).wait())]
                if sub is not None:
                    waiters.append(asyncio.ensure_future(
                        sub.cancel_event.wait()))
                try:
                    await asyncio.wait(
                        waiters, return_when=asyncio.FIRST_COMPLETED)
                finally:
                    for w in waiters:
                        w.cancel()
                if sub is not None and sub.cancelled:
                    raise _CancelledInFlight()
            ok, val = self.memory.get_nowait(oid)
            if ok and not isinstance(val, _StoreRef):
                if isinstance(val, TaskError):
                    raise val
                arg.kind = ArgKind.VALUE
                arg.value = val
                arg.object_id = None

    # ------------------------------------------------------- normal tasks
    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        if spec.is_streaming:
            self._streams[spec.task_id.hex()] = _StreamState()
        oids = spec.return_object_ids()
        self._mark_pending(oids)
        self._sched_event(spec, "QUEUED",
                          strategy=spec.scheduling.kind,
                          resources=dict(spec.resources.amounts),
                          poolable=self._poolable(spec))
        held = [a.object_id for a in spec.args
                if a.kind == ArgKind.OBJECT_REF and a.object_id is not None]
        self._add_submitted_holds(held)
        embedded = self._scan_embedded_refs(
            [a.value for a in spec.args if a.kind == ArgKind.VALUE])
        if embedded:
            self.promote_refs_to_plane(embedded)
        sub = _Submission(spec)
        for oid in oids:
            self._submissions[oid] = sub
        # Submission coalescing: a burst of .remote() calls from the
        # user thread wakes the io loop ONCE — the drain callback
        # spawns every buffered submission (call_soon_threadsafe is a
        # lock+futex pair per call otherwise; a 300-task batch paid
        # 300 of them).
        with self._submit_buf_lock:
            self._submit_buf.append((spec, sub, held))
            first = len(self._submit_buf) == 1
        if first:
            self.io.call_soon(self._drain_submit_buf)
        if spec.is_streaming:
            from .object_ref import ObjectRefGenerator

            return [ObjectRefGenerator(spec.task_id, oids[0], self)]
        return [ObjectRef(o) for o in oids]

    def _drain_submit_buf(self) -> None:
        """Io loop: spawn every submission buffered since the wakeup."""
        from .rpc import spawn_task

        with self._submit_buf_lock:
            batch, self._submit_buf = self._submit_buf, []
        for spec, sub, held in batch:
            spawn_task(self._submit_normal(spec, sub, held),
                       self.io.loop)

    async def _submit_normal(self, spec: TaskSpec,
                             sub: Optional[_Submission] = None,
                             held: Optional[List[ObjectID]] = None) -> None:
        sub = sub or _Submission(spec)
        try:
            await self._submit_normal_inner(spec, sub)
        finally:
            if held:
                self._release_submitted_holds(held)

    async def _submit_normal_inner(self, spec: TaskSpec,
                                   sub: _Submission) -> None:
        try:
            await self._resolve_deps(spec, sub)
        except _CancelledInFlight:
            self._fail_returns(spec, TaskError.from_exception(
                TaskCancelledError(
                    f"task {spec.display_name()} was cancelled")))
            return
        except TaskError as e:
            self._fail_returns(spec, e)
            return
        attempts_left = spec.max_retries
        if spec.is_streaming:
            # Streaming tasks never retry: items already delivered to
            # the consumer cannot be un-consumed, so a replay would
            # duplicate them (documented deviation: the reference
            # replays generators and dedups by item index).
            attempts_left = 0
        recoveries_left = 3  # bound on lost-arg reconstruct-and-retry
        delay = self.config.task_retry_delay_ms / 1000.0
        while True:
            try:
                if sub.cancelled:
                    raise _CancelledInFlight()
                if self._poolable(spec):
                    result = await self._submit_via_pool(spec, sub)
                else:
                    result = await self._lease_and_push(spec, sub)
            except _CancelledInFlight:
                self._fail_returns(spec, TaskError.from_exception(
                    TaskCancelledError(
                        f"task {spec.display_name()} was cancelled")))
                return
            except (RpcError, WorkerCrashedError) as e:
                if sub.cancelled:
                    # force-cancel killed the worker mid-push; report
                    # cancellation, not a crash, and never retry.
                    self._fail_returns(spec, TaskError.from_exception(
                        TaskCancelledError(
                            f"task {spec.display_name()} was cancelled")))
                    return
                if attempts_left != 0:
                    if attempts_left > 0:
                        attempts_left -= 1
                    spec.sched_attempt += 1
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 2.0)
                    continue
                self._fail_returns(spec, TaskError.from_exception(
                    WorkerCrashedError(str(e))))
                return
            except RemoteCallError as e:
                self._fail_returns(spec, TaskError.from_exception(e.cause))
                return
            if not result.ok:
                if getattr(result, "requeue", False):
                    # Direct-path push landed on a worker whose running
                    # task is blocked: resubmit through a fresh lease.
                    self._sched_event(spec, "REQUEUED",
                                      worker=sub.worker_addr,
                                      reason="worker_blocked")
                    await asyncio.sleep(0.01)
                    continue
                err = result.error
                if spec.is_streaming:
                    self._finalize_stream(spec, result)
                    return
                if isinstance(err, ObjectLostError) and not sub.cancelled \
                        and recoveries_left > 0 \
                        and await self._recover_lost_args(spec) \
                        and (recoveries_left := recoveries_left - 1) >= 0:
                    # An argument's copies were lost while the task was in
                    # flight; the owner reconstructed them — retry without
                    # consuming the user's retry budget (ref:
                    # task_manager.cc resubmit on OBJECT_UNRECONSTRUCTABLE
                    # is owner-driven, not a task failure).
                    spec.sched_attempt += 1
                    continue
                if spec.retry_exceptions and attempts_left != 0 \
                        and not sub.cancelled:
                    if attempts_left > 0:
                        attempts_left -= 1
                    spec.sched_attempt += 1
                    await asyncio.sleep(delay)
                    continue
                self._fail_returns(spec, err if isinstance(err, TaskError)
                                   else TaskError.from_exception(err))
                return
            self._accept_returns(spec, result)
            return

    async def _renv_blobs_present(self, key: str, wire) -> bool:
        """Throttled check that the controller still holds this env's
        package blobs — its KV applies an LRU cap (runtime_env_cache_
        bytes), and a worker spawned against an evicted blob fails.
        A positive result is cached for 30 s."""
        checked = getattr(self, "_renv_checked", None)
        if checked is None:
            checked = self._renv_checked = {}
        now = asyncio.get_event_loop().time()
        if now - checked.get(key, -1e9) < 30.0:
            return True
        digests = ([wire["working_dir_pkg"]]
                   if wire.get("working_dir_pkg") else []) + \
            [e["pkg"] for e in wire.get("py_modules_pkgs", [])]
        for digest in digests:
            found = await self._ctl.call(
                "kv_keys", {"prefix": f"runtime_env/pkg/{digest}"})
            if not found:
                checked.pop(key, None)
                return False
        checked[key] = now
        return True

    async def _runtime_env_payload(self, spec: TaskSpec):
        """Package + upload the task's runtime_env once per driver; the
        lease payload carries only the small wire spec (ref: worker
        pool keyed by runtime-env hash, worker_pool.h:216)."""
        raw = getattr(spec, "runtime_env", None)
        if not raw:
            return None
        import json as _json

        cache = getattr(self, "_renv_cache", None)
        if cache is None:
            cache = self._renv_cache = {}
        key = _json.dumps(raw, sort_keys=True)
        fut = cache.get(key)
        if fut is not None:
            # Concurrent submitters share one packaging pass; a cached
            # failure re-raises for every awaiter.
            wire = await fut
            if wire is None or await self._renv_blobs_present(key, wire):
                return wire
            cache.pop(key, None)  # blobs LRU-evicted: re-package below
            fut = None
        loop = asyncio.get_event_loop()
        fut = cache[key] = loop.create_future()
        from .. import runtime_env as renv

        try:
            # Zip + hash can be hundreds of MiB — keep it off the io
            # loop, which also carries every other RPC of this driver.
            wire, blobs = await loop.run_in_executor(
                None, lambda: renv.package(renv.normalize(raw) or {}))
            if len(wire) <= 1:  # only the hash of an empty env
                wire = None
            else:
                for kv_key, data in blobs.items():
                    existing = await self._ctl.call("kv_keys",
                                                    {"prefix": kv_key})
                    if not existing:
                        await self._ctl.call(
                            "kv_put", {"key": kv_key, "value": data})
        except (ValueError, TypeError) as e:
            # Surface as a task failure (the submit loop's except clauses
            # resolve the returns); never let it escape the io-loop task,
            # which would leave the ObjectRef unresolved forever.
            err = RemoteCallError(e)
            fut.set_exception(err)
            fut.exception()  # consumed; avoid 'never retrieved' warnings
            raise err from None
        except Exception as e:
            cache.pop(key, None)  # transient (e.g. RPC): allow retry
            fut.set_exception(e)
            fut.exception()
            raise
        fut.set_result(wire)
        return wire

    # ------------------------------------------- pooled lease submission
    # Ref: transport/normal_task_submitter.h:74,182 — the owner keeps a
    # per-scheduling-key task queue and a pool of granted leases; an
    # idle leased worker takes the next queued task directly (one push
    # RPC), a lease with no work is returned after a short keep-alive,
    # and at most `lease_request_limit` lease requests are in flight
    # per key (each advertising the remaining backlog for autoscaling).

    @staticmethod
    def _poolable(spec: TaskSpec) -> bool:
        # DEFAULT-strategy tasks only: SPREAD must hit the agent per
        # task to keep spreading, and PG/affinity-bound leases carry
        # placement state that must not outlive one task.
        return spec.scheduling.kind == "DEFAULT"

    def _sched_key(self, spec: TaskSpec, env_key: str) -> tuple:
        return (tuple(sorted(spec.resources.amounts.items())),
                spec.scheduling.kind, env_key, spec.job_id.hex())

    async def _submit_via_pool(self, spec: TaskSpec,
                               sub: _Submission) -> TaskResult:
        renv_wire = await self._runtime_env_payload(spec)
        env_key = (renv_wire or {}).get("hash", "") if renv_wire else ""
        key = self._sched_key(spec, env_key)
        st = self._sched_states.get(key)
        if st is None:
            payload = {
                "resources": dict(spec.resources.amounts),
                "strategy": spec.scheduling.kind,
                "job_id": spec.job_id.hex(),
            }
            if renv_wire is not None:
                payload["runtime_env"] = renv_wire
            st = self._sched_states[key] = _SchedKeyState(key, payload)
        if self._lease_sweeper is None:
            from .rpc import spawn_task

            self._lease_sweeper = spawn_task(self._lease_sweep_loop())
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        if spec.hp is not None:
            from ..util.hotpath import POOL_ENQUEUE

            spec.hp[POOL_ENQUEUE] = time.perf_counter()
        st.queue.append((spec, sub, fut,
                         asyncio.get_event_loop().time()))
        self._pump_key(st)
        waiters = [asyncio.ensure_future(fut),
                   asyncio.ensure_future(sub.cancel_event.wait())]
        try:
            await asyncio.wait(waiters,
                               return_when=asyncio.FIRST_COMPLETED)
        finally:
            waiters[1].cancel()
        if not fut.done():
            # Cancelled while still queued: the pump drops the entry.
            fut.cancel()
            raise _CancelledInFlight()
        return fut.result()  # re-raises push/lease errors

    def _pump_key(self, st: _SchedKeyState) -> None:
        """Assign queued tasks to idle pooled leases and top up lease
        requests toward min(backlog, lease_request_limit)."""
        from .rpc import spawn_task

        while st.idle:
            item = self._next_queued(st)
            if item is None:
                break
            pl = st.idle.pop()
            spawn_task(self._lease_worker_loop(st, pl, item))
        # Request NEW capacity only for items no about-to-idle lease
        # picked up within a beat (10ms) — a sequential caller's next
        # task otherwise races the lease loop's idle-append and spawns
        # a spurious lease request (and often a brand-new worker) per
        # call.  With no leases at all, request immediately (cold
        # start must not wait); the sweeper re-pumps every 100ms so
        # genuine backlog still scales out.
        if st.leases:
            now = asyncio.get_event_loop().time()
            # FIFO queue => enqueue times are ascending: the aged
            # items are a PREFIX, so stop at the first young one (and
            # at the request cap) — a full scan per submission would
            # be O(queue) and quadratic over a deep backlog.
            aged = 0
            cap = self.config.lease_request_limit
            for entry in st.queue:
                if now - entry[3] <= 0.01:
                    break
                if not entry[2].done():
                    aged += 1
                    if aged >= cap:
                        break
        else:
            aged = len(st.queue)
        want = min(aged, self.config.lease_request_limit)
        while len(st.request_agents) < want:
            rid = uuid.uuid4().hex
            st.request_agents[rid] = self.agent_addr
            spawn_task(self._request_pool_lease(st, rid))
        if aged < len(st.queue) and not st.repump_scheduled:
            # Some items are inside the request grace: re-pump just
            # after it expires so scale-out requests go out BEFORE the
            # (longer) pipeline grace lets a busy lease steal them —
            # fresh workers must win for long tasks to stay parallel.
            st.repump_scheduled = True

            def _repump():
                st.repump_scheduled = False
                if st.queue:
                    self._pump_key(st)

            asyncio.get_event_loop().call_later(0.015, _repump)

    def _next_queued(self, st: _SchedKeyState, min_age: float = 0.0):
        """Pop the next live queue item; with ``min_age``, only items
        queued at least that long (pipelining waits out the grace
        window so fresh lease grants keep long tasks parallel)."""
        now = asyncio.get_event_loop().time()
        while st.queue:
            head = st.queue[0]
            spec, sub, fut, t_enq = head
            if fut.done():
                st.queue.popleft()
                continue
            if sub.cancelled:
                st.queue.popleft()
                fut.set_exception(_CancelledInFlight())
                continue
            if min_age > 0.0 and now - t_enq < min_age:
                # Young item: hold it for a FRESH lease (the
                # delayed re-pump requests capacity at ~15ms; a
                # busy lease may only steal items older than
                # the pipeline grace).
                return None
            st.queue.popleft()
            return spec, sub, fut, t_enq
        return None

    async def _lease_worker_loop(self, st: _SchedKeyState,
                                 pl: _PooledLease, item=None) -> None:
        """Feed queued tasks to one leased worker with up to
        ``lease_pipeline_depth`` pushes in flight (ref: OnWorkerIdle +
        pipelining, normal_task_submitter.h:144).  The worker runs one
        task at a time from an explicit queue and hands back queued
        tasks if its running task blocks — a requeued item goes to
        the front of the owner queue for another lease."""
        from .rpc import spawn_task

        depth = max(1, self.config.lease_pipeline_depth)
        grace = self.config.lease_pipeline_grace_ms / 1000.0
        inflight: set = set()
        stalled = False   # worker reported blocked: stop feeding it
        stall_round = 0
        while True:
            batch = []
            while not pl.dead and not stalled \
                    and len(inflight) + len(batch) < depth:
                if item is not None:
                    nxt, item = item, None
                else:
                    # The FIRST task takes this worker immediately;
                    # extras pipeline only after the grace window (a
                    # fresh lease grant should claim young items so
                    # long tasks stay parallel).
                    nxt = self._next_queued(
                        st, min_age=0.0 if not (inflight or batch)
                        else grace)
                if nxt is None:
                    break
                batch.append(nxt)
            if batch:
                inflight.update(await self._exec_batch_send(
                    st, pl, batch, len(inflight)))
            pl.inflight = len(inflight)
            if not inflight:
                if pl.dead:
                    self._pump_key(st)
                    return
                if stalled:
                    # The worker is blocked on a task pushed by some
                    # OTHER owner: back off before probing again (an
                    # immediate probe would requeue-spin a hot notify
                    # loop against the blocked worker).
                    stalled = False
                    await asyncio.sleep(
                        min(0.005 * (2 ** min(stall_round, 5)), 0.1))
                    stall_round += 1
                    continue
                pl.idle_since = asyncio.get_event_loop().time()
                st.idle.append(pl)
                return
            if len(inflight) < depth and st.queue:
                # Head item still inside its grace window: re-check
                # shortly instead of sleeping until a push completes.
                done, inflight = await asyncio.wait(
                    inflight, timeout=grace,
                    return_when=asyncio.FIRST_COMPLETED)
            else:
                done, inflight = await asyncio.wait(
                    inflight, return_when=asyncio.FIRST_COMPLETED)
            statuses = {t.result() for t in done}
            if "requeue" in statuses:
                # A requeue in the batch wins over any "ok" from the
                # same round: the worker IS blocked right now, and an
                # arbitrary set-iteration order must not un-stall us
                # into bouncing more work off it.
                stalled = True
            elif "ok" in statuses:
                stalled = False
                stall_round = 0

    async def _exec_batch_send(self, st: _SchedKeyState,
                               pl: _PooledLease, items,
                               inflight_before: int = 0) -> list:
        """Ship a batch of tasks to a leased worker as ONE notify
        frame; per-item results come back batched as task_results
        notifies (ref: the push/report split in core_worker.proto —
        batching amortizes frame encode, syscalls, and context
        switches across the batch).  Returns one status future per
        item resolving to "ok" | "requeue" | "dead"."""
        loop = asyncio.get_event_loop()
        rfuts = []
        payload_tasks = []
        for pos, item in enumerate(items):
            spec, sub, fut, _t = item
            rid = next(self._reply_counter)
            sub.agent_addr = pl.agent_addr
            sub.worker_addr = pl.worker_addr
            sub.worker_id = pl.worker_id
            sub.pushed = True
            depth = inflight_before + pos
            self._sched_event(
                spec, "PIPELINED", lease_id=pl.lease_id,
                agent=pl.agent_addr, worker=pl.worker_addr,
                depth=depth,
                reason=("idle_lease" if depth == 0
                        else "pipelined_behind_busy_lease"))
            if spec.is_streaming:
                stream = self._streams.get(spec.task_id.hex())
                if stream is not None:
                    stream.worker_addr = pl.worker_addr
            rfut = loop.create_future()
            self._reply_waiters[rid] = ("pool", rfut, st, pl, item)
            if spec.hp is not None:
                from ..util.hotpath import OWNER_SEND

                spec.hp[OWNER_SEND] = time.perf_counter()
            payload_tasks.append({"spec": spec, "reply_id": rid})
            rfuts.append(rfut)
        try:
            worker = await self._worker_client(pl.worker_addr)
            await worker.notify("exec_batch", {
                "tasks": payload_tasks, "lease_id": pl.lease_id,
                "chip_ids": pl.chip_ids,
                "caller_tag": self.caller_tag})
        except Exception:  # noqa: BLE001 — handled as a dead lease
            self._on_worker_disconnect(pl.worker_addr)
        return rfuts

    def _on_task_results(self, payload: Dict) -> None:
        """Io loop: batched results from a leased worker."""
        for rid, res in payload["results"]:
            ent = self._reply_waiters.pop(rid, None)
            if ent is None:
                continue
            if ent[0] == "actor":
                _kind, afut, _addr = ent
                if not afut.done():
                    afut.set_result(res)
                continue
            _kind, rfut, st, pl, item = ent
            spec, sub, fut, _t = item
            if getattr(res, "requeue", False):
                # The worker's running task blocked in get(): fail
                # over to another lease, keeping rough order.
                self._sched_event(spec, "REQUEUED",
                                  lease_id=pl.lease_id,
                                  worker=pl.worker_addr,
                                  reason="worker_blocked")
                st.queue.appendleft(item)
                sub.pushed = False
                self._pump_key(st)
                if not rfut.done():
                    rfut.set_result("requeue")
                continue
            hp = getattr(res, "hp", None)
            if hp is not None:
                from ..util.hotpath import OWNER_REPLY_RECV

                hp[OWNER_REPLY_RECV] = time.perf_counter()
            if not fut.done():
                fut.set_result(res)
            if not rfut.done():
                rfut.set_result("ok")

    def _on_worker_disconnect(self, addr: str) -> None:
        """Io loop: a leased worker's connection died — fail its
        in-flight batched tasks (their submit loops retry) and release
        the lease."""
        err = RpcError(f"connection to {addr} lost")
        to_pump = {}
        for rid, ent in list(self._reply_waiters.items()):
            if ent[0] == "actor":
                # Don't fail the call outright: the reply frame may
                # have been LOST in a connection reregistration race
                # (the worker re-buffers undeliverable replies).
                # Re-dial — which re-registers our tag and triggers
                # the worker's redelivery — and only fail once the
                # grace expires (the PROGRESS reply-loss flake).
                _kind, afut, a_addr = ent
                if a_addr != addr:
                    continue
                if afut.done():
                    # Already resolved (e.g. caller-side cancel)
                    # with the entry still parked: no reply frame
                    # will ever pop it now that the worker is gone,
                    # so drop it here or it leaks forever.
                    self._reply_waiters.pop(rid, None)
                elif rid not in self._redelivering:
                    self._redelivering.add(rid)
                    from .rpc import spawn_task

                    spawn_task(self._await_reply_redelivery(
                        rid, afut, addr))
                continue
            _kind, rfut, st, pl, item = ent
            if pl.worker_addr != addr:
                continue
            self._reply_waiters.pop(rid, None)
            if not pl.dead:
                pl.dead = True
                st.leases.pop((pl.agent_addr, pl.lease_id), None)
                self._return_lease_async(pl, worker_failed=True)
            spec, sub, fut, _t = item
            if not fut.done():
                fut.set_exception(err)
            if not rfut.done():
                rfut.set_result("dead")
            to_pump[id(st)] = st
        for st in to_pump.values():
            self._pump_key(st)

    async def _await_reply_redelivery(self, rid: int, afut, addr: str
                                      ) -> None:
        """An actor-call reply's connection died with the call in
        flight.  Reconnect (re-registering the caller tag, which is
        the worker's redelivery trigger) and give the re-buffered
        reply a grace window to arrive before declaring the call
        lost.  A worker that is actually dead fails the re-dial, so
        real death still surfaces promptly."""
        grace = self.config.reply_redelivery_grace_s
        try:
            try:
                await self._worker_client(addr)
            except Exception:  # noqa: BLE001 — worker truly gone
                self._reply_waiters.pop(rid, None)
                if not afut.done():
                    afut.set_exception(RpcError(
                        f"connection to {addr} lost"))
                return
            try:
                await asyncio.wait_for(asyncio.shield(afut), grace)
                return  # redelivered (or resolved elsewhere)
            except asyncio.TimeoutError:
                pass
            except asyncio.CancelledError:
                # Either afut was cancelled caller-side or this task
                # is being torn down — in both cases the waiter entry
                # is dead and nothing else will remove it.
                self._reply_waiters.pop(rid, None)
                raise
            except Exception:  # noqa: BLE001 — resolved with error
                return
            self._reply_waiters.pop(rid, None)
            if not afut.done():
                afut.set_exception(RpcError(
                    f"connection to {addr} lost (reply not "
                    f"redelivered within {grace:.0f}s)"))
        finally:
            self._redelivering.discard(rid)

    async def _request_pool_lease(self, st: _SchedKeyState,
                                  rid: str) -> None:
        try:
            payload = dict(st.base_payload)
            payload["request_id"] = rid
            agent_addr = self.agent_addr
            hops = 0
            while True:
                st.request_agents[rid] = agent_addr
                agent = await self._agent_for(agent_addr)
                payload["owner_tag"] = self._owner_tag_for(agent_addr)
                grant = await agent.call("request_lease", payload)
                if grant is None:
                    raise RemoteCallError(RuntimeError(
                        f"agent {agent_addr} returned an empty lease "
                        f"grant"))
                if grant.get("cancelled"):
                    return  # queue drained; sweeper yanked the request
                if grant.get("ok"):
                    break
                if grant.get("retry_at") and hops < 8:
                    agent_addr = grant["retry_at"]
                    hops += 1
                    payload["no_spill"] = hops >= 4
                    continue
                raise RemoteCallError(ValueError(
                    grant.get("error", "lease request failed")))
            pl = _PooledLease(grant["lease_id"], agent_addr,
                              grant["worker_addr"],
                              grant.get("worker_id"),
                              grant.get("chip_ids", []))
            logger.debug("pool lease %s granted by %s (worker %s)",
                         pl.lease_id, agent_addr, grant["worker_addr"])
            # Keyed by (agent, id): lease ids are per-agent counters —
            # two agents both granting "lease 1" must not collide in
            # the pool (a collision silently leaks the overwritten
            # lease's CPU on its agent FOREVER; found via chaos test).
            st.leases[(pl.agent_addr, pl.lease_id)] = pl
            pl.idle_since = asyncio.get_event_loop().time()
            st.idle.append(pl)
            st.request_agents.pop(rid, None)
            self._pump_key(st)
        except (RpcError, RemoteCallError) as e:
            # Fail the queued tasks ONLY when this key has no other
            # way to serve them: no pooled lease (busy ones drain the
            # queue when they go idle) and no other in-flight request.
            # Otherwise one hop-capped or dropped request must not
            # take down tasks another lease would have run (the old
            # one-lease-per-task path only failed its own task).
            st.request_agents.pop(rid, None)
            if st.leases or st.request_agents:
                return
            while st.queue:
                _spec, _sub, fut, _t = st.queue.popleft()
                if not fut.done():
                    fut.set_exception(e)
        finally:
            st.request_agents.pop(rid, None)
            # A request can resolve {cancelled} in a race with a task
            # that enqueued AFTER the sweeper fired the cancel; the
            # pump would then never run again for this key (the
            # sweeper only acts on empty queues).  Re-pump ONLY when
            # nothing else can serve the queue — a busy lease drains
            # it when it goes idle, and pumping while a failing agent
            # is the only target would spin request/fail with no
            # backoff.
            if st.queue and not st.leases and not st.request_agents:
                self._pump_key(st)

    def _return_lease_async(self, pl: _PooledLease,
                            worker_failed: bool = False) -> None:
        from .rpc import spawn_task

        async def _ret():
            try:
                agent = await self._agent_for(pl.agent_addr)
                await agent.call("return_lease", {
                    "lease_id": pl.lease_id,
                    "worker_failed": worker_failed})
                logger.debug("returned lease %s to %s (failed=%s)",
                             pl.lease_id, pl.agent_addr, worker_failed)
            except (RpcError, RemoteCallError) as e:
                logger.debug("return of lease %s to %s failed: %r",
                             pl.lease_id, pl.agent_addr, e)

        spawn_task(_ret(), self.io.loop)

    async def _lease_sweep_loop(self) -> None:
        """Return leases idle past the keep-alive, cancel lease
        requests whose backlog drained, and refresh the per-key
        backlog the local agent advertises as autoscaler demand (ref:
        lease_timeout_ms_ + CancelWorkerLeaseIfNeeded +
        ReportWorkerBacklog in normal_task_submitter.h — backlog is a
        periodic report per scheduling key, NOT a field frozen into a
        queued lease request for up to an hour)."""
        last_backlog: Dict[tuple, int] = {}
        last_pool_report = 0.0
        while not self._shutdown_flag:
            await asyncio.sleep(0.1)
            now = asyncio.get_event_loop().time()
            ttl = self.config.lease_keepalive_s
            if now - last_pool_report >= 0.45:
                last_pool_report = now
                await self._report_lease_pools()
            for key, st in list(self._sched_states.items()):
                if st.queue:
                    # Re-pump: items past the request grace get their
                    # scale-out lease requests here.
                    self._pump_key(st)
                # Queue size BEYOND in-flight lease requests (each
                # queued request already stands for one task in the
                # agent's demand vector).
                backlog = max(0, len(st.queue) - len(st.request_agents))
                if backlog != last_backlog.get(key) or backlog:
                    last_backlog[key] = backlog
                    try:
                        await self._agent.notify("report_backlog", {
                            "owner": self._runtime_id,
                            "key": repr(key),
                            "resources": dict(
                                st.base_payload["resources"]),
                            "backlog": backlog})
                    except (RpcError, OSError):
                        pass
                if not st.queue:
                    for rid, agent_addr in list(st.request_agents.items()):
                        self._cancel_lease_request_async(rid, agent_addr)
                    for pl in [p for p in st.idle
                               if now - p.idle_since > ttl]:
                        st.idle.remove(pl)
                        st.leases.pop((pl.agent_addr, pl.lease_id),
                                      None)
                        self._return_lease_async(pl)
                if not st.queue and not st.leases \
                        and not st.request_agents:
                    self._sched_states.pop(key, None)
                    last_backlog.pop(key, None)

    async def _report_lease_pools(self) -> None:
        """Ship this owner's pooled-lease pipeline depths to the
        granting agents (sweeper cadence) so the agent's lease ledger
        — `rt list leases` — shows how deep each held lease is
        pipelined, owner-side state the agent cannot observe."""
        by_agent: Dict[str, Dict[int, int]] = {}
        for st in self._sched_states.values():
            for pl in st.leases.values():
                if not pl.dead:
                    by_agent.setdefault(pl.agent_addr, {})[
                        pl.lease_id] = pl.inflight
        for addr, leases in by_agent.items():
            # Never DIAL for this: the report rides the sweep loop,
            # and a blackholed peer agent would block every sweep
            # duty (re-pump, idle returns, backlog reports) for the
            # whole connect timeout.  Only already-connected clients
            # get the notify; a lease implies one normally exists.
            if addr == self.agent_addr:
                agent = self._agent
            else:
                agent = getattr(self, "_peer_agent_clients",
                                {}).get(addr)
            if agent is None or not agent.connected:
                continue
            try:
                # notify_nowait, not notify: notify() awaits drain(),
                # and a peer that is connected but not reading would
                # park the sweep loop on transport backpressure — the
                # same every-sweep-duty stall the no-DIAL rule above
                # exists to prevent, just one layer down.
                agent.notify_nowait("report_lease_pool", {
                    "owner": self._runtime_id, "leases": leases})
            except (RpcError, RemoteCallError, OSError):
                pass

    def _cancel_lease_request_async(self, rid: str,
                                    agent_addr: str) -> None:
        from .rpc import spawn_task

        async def _cancel():
            try:
                agent = await self._agent_for(agent_addr)
                await agent.call("cancel_lease_request",
                                 {"request_id": rid})
            except (RpcError, RemoteCallError):
                pass

        spawn_task(_cancel(), self.io.loop)

    async def _lease_and_push(self, spec: TaskSpec,
                              sub: _Submission) -> TaskResult:
        payload = {
            "resources": dict(spec.resources.amounts),
            "strategy": spec.scheduling.kind,
            "request_id": sub.request_id,
            "job_id": spec.job_id.hex(),
        }
        renv_wire = await self._runtime_env_payload(spec)
        if renv_wire is not None:
            payload["runtime_env"] = renv_wire
        if spec.scheduling.kind == "PLACEMENT_GROUP":
            payload["pg_id"] = spec.scheduling.placement_group_id
            payload["bundle_index"] = spec.scheduling.bundle_index
        agent_addr = self.agent_addr
        if spec.scheduling.kind == "NODE_AFFINITY" and \
                spec.scheduling.node_id is not None:
            addr = await self._agent_addr_of(spec.scheduling.node_id)
            if addr is not None:
                agent_addr = addr
                payload["no_spill"] = True
        if spec.scheduling.kind == "PLACEMENT_GROUP":
            addr = await self._pg_agent_addr(payload["pg_id"],
                                             payload["bundle_index"])
            if addr is not None:
                agent_addr = addr
        # Lease loop with spillback redirects (ref:
        # normal_task_submitter.h:182 RequestNewWorkerIfNeeded).
        hops = 0
        while True:
            sub.agent_addr = agent_addr
            agent = await self._agent_for(agent_addr)
            payload["owner_tag"] = self._owner_tag_for(agent_addr)
            self._sched_event(spec, "LEASE_REQUESTED",
                              agent=agent_addr, hops=hops,
                              strategy=spec.scheduling.kind,
                              reason=("spillback_redirect" if hops
                                      else "local_agent"))
            logger.debug("lease req %s -> %s (hops=%d)",
                         spec.display_name(), agent_addr, hops)
            grant = await agent.call("request_lease", payload)
            logger.debug("lease rsp %s <- %s: %s", spec.display_name(),
                         agent_addr, grant and
                         {k: grant[k] for k in ("ok", "retry_at", "error",
                                                "lease_id")
                          if k in grant})
            if grant is None:  # defensive: agent bug, not retryable
                raise RemoteCallError(RuntimeError(
                    f"agent {agent_addr} returned an empty lease grant"))
            if grant.get("cancelled") or sub.cancelled:
                if grant.get("ok"):
                    await agent.call("return_lease",
                                     {"lease_id": grant["lease_id"]})
                raise _CancelledInFlight()
            if grant.get("ok"):
                break
            if grant.get("retry_at") and hops < 8:
                agent_addr = grant["retry_at"]
                hops += 1
                payload["no_spill"] = hops >= 4
                continue
            raise RemoteCallError(ValueError(
                grant.get("error", "lease request failed")))
        lease_id = grant["lease_id"]
        node_id = grant.get("node_id")
        self._sched_event(spec, "GRANTED", lease_id=lease_id,
                          agent=agent_addr,
                          node=(node_id.hex() if hasattr(node_id,
                                                         "hex")
                                else node_id),
                          worker=grant["worker_addr"], hops=hops)
        sub.worker_addr = grant["worker_addr"]
        sub.worker_id = grant.get("worker_id")
        sub.pushed = True
        if spec.is_streaming:
            stream = self._streams.get(spec.task_id.hex())
            if stream is not None:
                stream.worker_addr = grant["worker_addr"]
        try:
            worker = await self._worker_client(grant["worker_addr"])
            reply = await worker.call("push_task", {
                "spec": spec, "chip_ids": grant.get("chip_ids", []),
                "lease_id": lease_id,
                "caller_tag": self.caller_tag})
            return reply
        finally:
            try:
                await agent.call("return_lease", {"lease_id": lease_id})
            except RpcError:
                pass

    _peer_agent_clients: Dict[str, RpcClient]

    def _owner_tag_for(self, agent_addr: str) -> str:
        """The connection tag this process uses toward ``agent_addr`` —
        sent with lease requests so the agent can reclaim leases whose
        owner process died without returning them (the agent watches
        the tagged connection; see node_agent._on_owner_conn_lost)."""
        return (f"rt-{os.getpid()}" if agent_addr == self.agent_addr
                else f"rt-peer-{self._runtime_id}")

    async def _agent_for(self, addr: str) -> RpcClient:
        if addr == self.agent_addr:
            return self._agent
        if not hasattr(self, "_peer_agent_clients"):
            self._peer_agent_clients = {}
        cli = self._peer_agent_clients.get(addr)
        if cli is None or not cli.connected:
            cli = RpcClient(addr, tag=f"rt-peer-{self._runtime_id}")
            await cli.connect()
            self._peer_agent_clients[addr] = cli
        return cli

    async def _agent_addr_of(self, node_id: NodeID) -> Optional[str]:
        nodes = await self._ctl.call("list_nodes", {})
        for n in nodes:
            if n["node_id"] == node_id and n["alive"]:
                return n["agent_addr"]
        return None

    async def _pg_agent_addr(self, pg_id, bundle_index) -> Optional[str]:
        deadline = asyncio.get_event_loop().time() + 60.0
        while asyncio.get_event_loop().time() < deadline:
            info = await self._ctl.call("get_placement_group",
                                        {"pg_id": pg_id})
            if info is None:
                return None
            if info["state"] == "CREATED":
                if bundle_index < 0:
                    # Any bundle's node will do; pick the first.
                    placement = info["placement"]
                    if placement:
                        return next(iter(placement.values()))["agent_addr"]
                    return None
                ent = info["placement"].get(bundle_index)
                return ent["agent_addr"] if ent else None
            if info["state"] == "REMOVED":
                return None
            await asyncio.sleep(0.05)
        return None

    def _fail_returns(self, spec: TaskSpec, err: TaskError) -> None:
        if spec.is_streaming:
            self._finalize_stream(spec, None, error=err)
            return
        for oid in spec.return_object_ids():
            sub = self._submissions.pop(oid, None)
            if sub is not None:
                sub.done = True
            self._store_result_value(oid, err)

    def _accept_returns(self, spec: TaskSpec, result: TaskResult) -> None:
        if spec.is_streaming:
            self._finalize_stream(spec, result)
            return
        hp = getattr(result, "hp", None)
        if hp is not None:
            self._hotpath_record(spec, hp)
        from . import serialization

        oids = spec.return_object_ids()
        for oid, (kind, data) in zip(oids, result.returns):
            sub = self._submissions.pop(oid, None)
            if sub is not None:
                sub.done = True
            if kind == "inline":
                # Unpacking materializes any embedded ObjectRefs, whose
                # __init__ hooks register this process's borrows (queued
                # on this same connection, so they reach the controller
                # before the transit release below).
                self._store_result_value(oid, serialization.unpack(data))
            else:  # ("store", (size, node_hint))
                size, node_hint = data
                with self._refs_lock:
                    self._owned_plane.add(oid)
                    if spec.kind == TaskKind.NORMAL:
                        # Deterministic re-execution source for recovery;
                        # actor results are not reconstructable (state).
                        self._lineage[oid] = spec
                self._store_result_value(oid, _StoreRef(size, node_hint))
        # Ownership handoff complete: drop the worker's transit borrows on
        # refs that travelled inside inline return values (the worker
        # registered them before its own references died).
        for emb in getattr(result, "transit_refs", None) or []:
            self._notify_async("remove_borrower", {
                "object_id": emb,
                "holder": f"transit:{spec.task_id.hex()}"})

    # ------------------------------------------------------------- actors
    def create_actor(self, spec: TaskSpec) -> None:
        payload = {
            "spec": spec, "class_name": spec.name.split(".")[0],
            "method_names": spec.method_names,
            "detached": spec.lifetime == "detached",
            "owner_addr": self._runtime_id}
        if spec.actor_name:
            # Named actors keep the synchronous path: the name-conflict
            # refusal must raise HERE, in the caller's frame.
            r = self.io.run(self._ctl.call("register_actor", payload))
            if not r.get("ok"):
                raise ValueError(
                    r.get("error", "actor registration failed"))
            payload = None  # already registered
        else:
            self._actor_reg_pending[spec.actor_id] = True
        held = [a.object_id for a in spec.args
                if a.kind == ArgKind.OBJECT_REF and a.object_id is not None]
        self._add_submitted_holds(held)
        self.io.call_soon(lambda: self.io.loop.create_task(
            self._create_actor_async(spec, held, payload)))

    async def _create_actor_async(self, spec: TaskSpec,
                                  held: Optional[List[ObjectID]]
                                  = None,
                                  reg_payload: Optional[Dict]
                                  = None) -> None:
        try:
            if reg_payload is not None:
                # Unnamed actor: registration rides the coalescing
                # batch (it cannot hit a name conflict, so deferring
                # the result off the caller's thread loses nothing).
                try:
                    r = await self._register_actor_batched(reg_payload)
                except (RpcError, RemoteCallError) as e:
                    r = {"ok": False, "error": repr(e)}
                finally:
                    self._actor_reg_pending.pop(spec.actor_id, None)
                if not r.get("ok"):
                    # The controller never learned this actor exists,
                    # so callers polling get_actor would only see an
                    # opaque "unknown actor" after the full grace.
                    # Leave a LOCAL terminal cache entry instead: the
                    # first method call fails fast with the real
                    # registration error (death_reason set marks it
                    # as locally authoritative — controller-mirrored
                    # DEAD entries from the event poll carry none).
                    reason = (f"actor registration failed: "
                              f"{r.get('error', 'unknown error')}")
                    logger.warning("actor %s: %s",
                                   spec.actor_id.hex()[:8], reason)
                    self._actor_cache[spec.actor_id] = {
                        "actor_id": spec.actor_id, "state": "DEAD",
                        "worker_addr": "", "death_reason": reason,
                        "class_name": spec.name.split(".")[0],
                        "method_names": spec.method_names,
                        "max_concurrency": spec.max_concurrency,
                        "concurrency_groups": {},
                        "method_options": {}}
                    return
            await self._create_actor_inner(spec)
        finally:
            self._actor_reg_pending.pop(spec.actor_id, None)
            if held:
                self._release_submitted_holds(held)

    async def _register_actor_batched(self, payload: Dict) -> Dict:
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._actor_reg_buf.append((payload, fut))
        if self._actor_reg_flusher is None or \
                self._actor_reg_flusher.done():
            from .rpc import spawn_task

            self._actor_reg_flusher = spawn_task(
                self._flush_actor_regs())
        return await fut

    async def _flush_actor_regs(self) -> None:
        """Drain the registration buffer in bulk register_actors RPCs;
        the 5 ms sleep IS the coalescing window (everything enqueued
        while a flush's RPC is in flight batches into the next)."""
        while self._actor_reg_buf:
            await asyncio.sleep(0.005)
            items, self._actor_reg_buf = self._actor_reg_buf, []
            if not items:
                continue
            try:
                r = await self._ctl.call(
                    "register_actors",
                    {"items": [p for p, _f in items]})
                results = r.get("results") or []
            except (RpcError, RemoteCallError) as e:
                for _p, fut in items:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            for (_p, fut), res in zip(items, results):
                if not fut.done():
                    fut.set_result(res if res is not None
                                   else {"ok": False})
            for _p, fut in items[len(results):]:
                if not fut.done():
                    fut.set_result({"ok": False,
                                    "error": "short bulk reply"})

    async def _create_actor_inner(self, spec: TaskSpec) -> None:
        """Creation-path fault tolerance (ref: gcs_actor_manager.h:90
        — creation failures from infrastructure (node/worker death)
        reschedule the actor elsewhere; only user-code failures and
        placement impossibility are terminal).  The lease+push loop
        below retries RpcErrors with backoff long enough to outlive
        the health-check window during which a dying node still looks
        routable."""
        last_err: Optional[BaseException] = None
        for attempt in range(6):
            if attempt:
                # A previous attempt MAY have reached the worker right
                # before its connection died; if the actor came up,
                # creating a second instance would be worse than wrong.
                try:
                    info = await self._ctl.call(
                        "get_actor", {"actor_id": spec.actor_id})
                except RpcError:
                    info = None
                if info is not None and info.get("state") == "ALIVE":
                    return
                await asyncio.sleep(min(0.2 * (2 ** (attempt - 1)),
                                        2.0))
            try:
                await self._create_actor_attempt(spec)
                return
            except RpcError as e:
                # Infrastructure: agent/worker connection lost mid-
                # create (a node going down) — retry on fresh routing.
                last_err = e
                continue
            except (RemoteCallError, ValueError) as e:
                last_err = e
                break
        try:
            await self._ctl.call("actor_died", {
                "actor_id": spec.actor_id, "creation_failed": True,
                "reason": f"creation failed: {last_err}"})
        except RpcError:
            pass

    async def _create_actor_attempt(self, spec: TaskSpec) -> None:
        try:
            await self._resolve_deps(spec)
            payload = {
                "resources": dict(spec.resources.amounts),
                "strategy": spec.scheduling.kind,
                "is_actor": True, "actor_id": spec.actor_id,
                "job_id": spec.job_id.hex(),
            }
            renv_wire = await self._runtime_env_payload(spec)
            if renv_wire is not None:
                payload["runtime_env"] = renv_wire
            if spec.scheduling.kind == "PLACEMENT_GROUP":
                payload["pg_id"] = spec.scheduling.placement_group_id
                payload["bundle_index"] = spec.scheduling.bundle_index
            agent_addr = self.agent_addr
            if spec.scheduling.kind == "PLACEMENT_GROUP":
                addr = await self._pg_agent_addr(payload["pg_id"],
                                                 payload["bundle_index"])
                if addr is not None:
                    agent_addr = addr
            elif spec.scheduling.kind == "NODE_AFFINITY" and \
                    spec.scheduling.node_id is not None:
                addr = await self._agent_addr_of(spec.scheduling.node_id)
                if addr is not None:
                    agent_addr = addr
                    payload["no_spill"] = True
            hops = 0
            while True:
                agent = await self._agent_for(agent_addr)
                grant = await agent.call("request_lease", payload)
                if grant.get("ok"):
                    break
                if grant.get("retry_at") and hops < 8:
                    agent_addr = grant["retry_at"]
                    hops += 1
                    continue
                raise ValueError(grant.get("error", "lease failed"))
            worker = await self._worker_client(grant["worker_addr"])
            r = await worker.call("create_actor", {
                "spec": spec, "chip_ids": grant.get("chip_ids", []),
                "lease_id": grant["lease_id"]})
            if r.get("ok"):
                # The worker's reply means actor_started committed at
                # the controller, so the first method call can skip
                # the get_actor poll entirely — prime the cache with
                # the fields _actor_info consumers read.  A later
                # death still invalidates it (the pubsub actor-event
                # hook and the submit paths pop dead entries).
                self._actor_cache[spec.actor_id] = {
                    "actor_id": spec.actor_id, "state": "ALIVE",
                    "worker_addr": grant["worker_addr"],
                    "class_name": spec.name.split(".")[0],
                    "method_names": spec.method_names,
                    "death_reason": "",
                    "max_concurrency": spec.max_concurrency,
                    "concurrency_groups": dict(
                        getattr(spec, "concurrency_groups", {}) or {}),
                    "method_options": dict(
                        getattr(spec, "method_options", {}) or {})}
        except RpcError:
            raise  # infra failure: _create_actor_inner retries
        except (RemoteCallError, ValueError):
            raise  # terminal: user code / placement impossibility

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        if spec.is_streaming:
            self._streams[spec.task_id.hex()] = _StreamState()
        oids = spec.return_object_ids()
        self._mark_pending(oids)
        if spec.is_streaming:
            # cancel(gen) must find a routable submission — actor
            # tasks normally have none, but a runaway stream needs
            # the worker-side cancel path.
            sub = _Submission(spec)
            for oid in oids:
                self._submissions[oid] = sub
        held = [a.object_id for a in spec.args
                if a.kind == ArgKind.OBJECT_REF and a.object_id is not None]
        self._add_submitted_holds(held)
        embedded = self._scan_embedded_refs(
            [a.value for a in spec.args if a.kind == ArgKind.VALUE])
        if embedded:
            self.promote_refs_to_plane(embedded)
        self.io.call_soon(lambda: self.io.loop.create_task(
            self._submit_actor(spec, held)))
        if spec.is_streaming:
            from .object_ref import ObjectRefGenerator

            return [ObjectRefGenerator(spec.task_id, oids[0], self)]
        return [ObjectRef(o) for o in oids]

    async def _actor_info(self, actor_id: ActorID,
                          wait_alive: bool = True,
                          timeout: Optional[float] = None) -> Dict:
        if timeout is None:
            timeout = self.config.actor_ready_timeout_s
        deadline = asyncio.get_event_loop().time() + timeout
        # A batched registration still in flight means get_actor would
        # read "unknown actor" spuriously — wait the 5 ms window out
        # (bounded by the ready deadline like every other wait here).
        while actor_id in self._actor_reg_pending and \
                asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.002)
        # A handle can also cross PROCESSES inside the creator's
        # batching window (serve controller -> driver): grant unknown
        # actors a short grace before declaring them dead, so the
        # remote registration flush can land.
        unknown_grace = asyncio.get_event_loop().time() + \
            min(5.0, timeout)
        delay = 0.02
        while True:
            info = self._actor_cache.get(actor_id)
            if info is not None and info["state"] == "DEAD" and \
                    info.get("death_reason"):
                # Locally-authoritative terminal entry (e.g. the
                # batched registration failed, so the controller has
                # no record to poll): fail fast with the real reason.
                raise ActorDiedError(actor_id.hex(),
                                     info["death_reason"])
            if info is None or info["state"] not in ("ALIVE",) or \
                    not info.get("worker_addr"):
                info = await self._ctl.call("get_actor",
                                            {"actor_id": actor_id})
                if info is not None:
                    self._actor_cache[actor_id] = info
            if info is None:
                if wait_alive and \
                        asyncio.get_event_loop().time() < unknown_grace:
                    await asyncio.sleep(delay)
                    delay = min(delay * 1.5, 0.5)
                    continue
                raise ActorDiedError(actor_id.hex(), "unknown actor")
            if info["state"] == "ALIVE" and info.get("worker_addr"):
                return info
            if info["state"] == "DEAD":
                raise ActorDiedError(
                    actor_id.hex(), info.get("death_reason") or "actor dead")
            if not wait_alive or \
                    asyncio.get_event_loop().time() > deadline:
                raise ActorDiedError(actor_id.hex(),
                                     f"actor stuck in {info['state']}")
            await asyncio.sleep(delay)
            delay = min(delay * 1.5, 0.5)

    async def _submit_actor(self, spec: TaskSpec,
                            held: Optional[List[ObjectID]] = None) -> None:
        """Actor calls execute in submission order for max_concurrency=1
        actors: the per-actor lock is taken in coroutine creation order
        (FIFO) and held across dep resolution + push, so the worker's
        single-threaded executor receives them in program order — and a
        restarted actor needs no seq handshake (ref: the role of
        ActorSubmitQueue in transport/actor_task_submitter.h, redesigned
        around in-order connection delivery)."""
        try:
            ordered = spec.max_concurrency <= 1 and not spec.unordered
            if ordered and spec.max_retries == 0:
                # Pipelined fast path: the submit lock covers only
                # dep-resolution + the frame WRITE, so wire order (and
                # therefore worker execution order) still equals
                # program order while replies overlap.  Retriable actor
                # methods take the serial path below — a retry after a
                # pipelined failure could execute behind younger calls,
                # which the lock-across-reply path can't.
                await self._submit_actor_pipelined(spec)
            elif ordered:
                lock = self._actor_submit_locks.setdefault(
                    spec.actor_id, asyncio.Lock())
                async with lock:
                    await self._submit_actor_inner(spec)
            else:
                await self._submit_actor_inner(spec)
        finally:
            if held:
                self._release_submitted_holds(held)

    async def _submit_actor_pipelined(self, spec: TaskSpec) -> None:
        lock = self._actor_submit_locks.setdefault(
            spec.actor_id, asyncio.Lock())
        fut = None
        worker = None
        async with lock:
            try:
                await self._resolve_deps(spec)
            except TaskError as e:
                self._fail_returns(spec, e)
                return
            try:
                info = await self._actor_info(spec.actor_id)
            except ActorDiedError as e:
                self._fail_returns(spec, ActorError.from_exception(e))
                return
            try:
                if spec.is_streaming:
                    stream = self._streams.get(spec.task_id.hex())
                    if stream is not None:
                        stream.worker_addr = info["worker_addr"]
                    ssub = self._submissions.get(
                        spec.return_object_ids()[0])
                    if ssub is not None:
                        ssub.worker_addr = info["worker_addr"]
                        ssub.pushed = True
                worker = await self._worker_client(info["worker_addr"])
                rid = next(self._reply_counter)
                fut = asyncio.get_event_loop().create_future()
                self._reply_waiters[rid] = (
                    "actor", fut, info["worker_addr"])
                try:
                    worker.notify_nowait("exec_actor", {
                        "spec": spec, "reply_id": rid,
                        "caller_id": self._runtime_id,
                        "caller_tag": self.caller_tag})
                except RpcError:
                    self._reply_waiters.pop(rid, None)
                    fut = None
            except RpcError:
                fut = None  # dial failed: serial path refreshes state
            if fut is None:
                self._actor_cache.pop(spec.actor_id, None)
                await self._submit_actor_inner(spec)
                return
        await worker.drain()
        try:
            reply = await fut
        except RpcError:
            # Connection died with the call in flight.  No retry budget
            # on this path (max_retries == 0): resolve to death/loss the
            # way the serial path's no-budget branch does.
            self._actor_cache.pop(spec.actor_id, None)
            try:
                await self._actor_info(spec.actor_id, timeout=5.0)
                reason = "actor task connection lost mid-call"
            except ActorDiedError as de:
                reason = str(de.reason)
            self._fail_returns(spec, ActorError.from_exception(
                ActorDiedError(spec.actor_id.hex(), reason)))
            return
        except RemoteCallError as e:
            self._fail_returns(spec, ActorError.from_exception(e.cause))
            return
        if not reply.ok:
            err = reply.error
            self._fail_returns(spec, err if isinstance(err, TaskError)
                               else ActorError.from_exception(err))
            return
        self._accept_returns(spec, reply)

    async def _submit_actor_inner(self, spec: TaskSpec) -> None:
        try:
            await self._resolve_deps(spec)
        except TaskError as e:
            self._fail_returns(spec, e)
            return
        attempts_left = spec.max_retries
        while True:
            try:
                info = await self._actor_info(spec.actor_id)
            except ActorDiedError as e:
                self._fail_returns(spec, ActorError.from_exception(e))
                return
            try:
                if spec.is_streaming:
                    stream = self._streams.get(spec.task_id.hex())
                    if stream is not None:
                        stream.worker_addr = info["worker_addr"]
                    ssub = self._submissions.get(
                        spec.return_object_ids()[0])
                    if ssub is not None:
                        ssub.worker_addr = info["worker_addr"]
                        ssub.pushed = True
                worker = await self._worker_client(info["worker_addr"])
                reply = await worker.call("push_actor_task", {
                    "spec": spec, "caller_id": self._runtime_id,
                    "caller_tag": self.caller_tag})
            except (RpcError, RemoteCallError) as e:
                # Worker gone: refresh state; retry while restarting if the
                # method has a retry budget, else surface death.
                self._actor_cache.pop(spec.actor_id, None)
                if isinstance(e, RemoteCallError):
                    self._fail_returns(spec,
                                       ActorError.from_exception(e.cause))
                    return
                if attempts_left != 0:
                    if attempts_left > 0:
                        attempts_left -= 1
                    await asyncio.sleep(0.1)
                    continue
                try:
                    await self._actor_info(spec.actor_id, timeout=5.0)
                    reason = "actor task connection lost mid-call"
                except ActorDiedError as de:
                    reason = str(de.reason)
                self._fail_returns(spec, ActorError.from_exception(
                    ActorDiedError(spec.actor_id.hex(), reason)))
                return
            if not reply.ok:
                err = reply.error
                self._fail_returns(spec, err if isinstance(err, TaskError)
                                   else ActorError.from_exception(err))
                return
            self._accept_returns(spec, reply)
            return

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        self._actor_cache.pop(actor_id, None)

        async def _kill():
            # A kill racing this owner's own batched registration
            # would reach the controller BEFORE the actor exists and
            # be silently ignored — the actor would then start and
            # run forever.  Wait the coalescing window out (bounded),
            # like _actor_info does for method calls.
            deadline = asyncio.get_event_loop().time() + 10.0
            while actor_id in self._actor_reg_pending and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.002)
            return await self._ctl.call("kill_actor", {
                "actor_id": actor_id, "no_restart": no_restart})

        self.io.run(_kill())

    def get_named_actor(self, name: str, namespace: str = ""):
        info = self.io.run(self._ctl.call("lookup_named_actor", {
            "name": name, "namespace": namespace}))
        if info is None or info["state"] == "DEAD":
            raise ValueError(f"No actor named {name!r} in namespace "
                             f"{namespace!r}")
        from .api import ActorHandle

        groups = info.get("concurrency_groups") or {}
        return ActorHandle(info["actor_id"], info["class_name"],
                           info["method_names"], namespace,
                           info.get("max_concurrency", 1),
                           has_groups=bool(groups),
                           method_options=info.get("method_options"),
                           group_names=sorted(groups))

    # ------------------------------------------------------------- objects
    def put(self, value: Any) -> ObjectRef:
        from . import serialization
        from .object_ref import collect_embedded_refs

        oid = ObjectID.for_put(self.current_task_id(), self.next_put_index())
        with collect_embedded_refs() as embedded:
            payload, views = serialization.serialize(value)
        if embedded:
            # Refs nested in a put payload escape to whoever gets the
            # container: their in-band values must be pullable.
            self.promote_refs_to_plane(list(embedded))
        size = self.store.seal_parts(oid, payload, views)
        with self._refs_lock:
            self._owned_ids.add(oid)
            self._owned_plane.add(oid)  # puts have no lineage (ref parity)
            if not embedded:
                # Eligible for the agent-local fast release: a plain
                # put with no embedded refs has no induced borrows to
                # cascade on the controller.
                self._local_puts.add(oid)
        # Fire-and-forget registration, written from THIS thread over
        # the notify side channel — the sealed bytes are already
        # readable locally (get() maps them directly) and remote pulls
        # poll the directory with re-checks, so registration latency
        # is absorbed; skipping the io-loop wakeup + round trip
        # removes most of the driver-side cost of a large put.
        if not self._side_channel.notify(
                "register_object", {"object_id": oid, "size": size}):
            # Side channel down: fall back to an ACKED call on the main
            # agent connection — a notify here could be swallowed by a
            # half-open socket's deferred flush, silently leaving the
            # object unregistered (remote pulls would hang forever).
            def _send_register():
                def _check(f):
                    if f.cancelled() or f.exception() is not None:
                        asyncio.ensure_future(
                            self._register_object_retry(oid, size))

                try:
                    self._agent.call_nowait(
                        "register_object",
                        {"object_id": oid, "size": size}
                    ).add_done_callback(_check)
                except Exception:
                    # Not connected (reconnect window): full dial.
                    asyncio.ensure_future(
                        self._register_object_retry(oid, size))

            self._bg_submit(_send_register)
        self.memory.put(oid, _StoreRef(size))
        return ObjectRef(oid)

    async def _register_object_retry(self, oid: ObjectID,
                                     size: int) -> None:
        try:
            await self._agent.call("register_object",
                                   {"object_id": oid, "size": size})
        except (RpcError, RemoteCallError):
            pass  # agent gone: the node (and this copy) is dying anyway

    # Worker-role callback (set by worker_main): fired when the
    # executing task blocks/unblocks in get().
    on_block = None

    def _notify_blocked(self, blocked: bool) -> None:
        """Worker-role hook: release/reacquire lease CPU while blocked in
        get (driver has no lease; no-op)."""
        if self.on_block is not None:
            try:
                self.on_block(blocked)
            except Exception:
                pass
        lease_id = self.current_lease_id
        if lease_id is None:
            return
        method = "task_blocked" if blocked else "task_unblocked"
        try:
            self.io.run(self._agent.call(method, {"lease_id": lease_id}),
                        timeout=5.0)
        except Exception:
            pass

    def _fetch_store_value(self, oid: ObjectID,
                           timeout: Optional[float],
                           size_hint: int = 0) -> Any:
        """Pull a plane object into the local node store and map it,
        reconstructing from lineage if every copy was lost.

        ``size_hint`` > 0 means the caller already knows the object's
        packed size (a _StoreRef descriptor — our own put or a local
        task result): try mapping the local store directly before
        paying the agent pull round trip.  Both backends make the
        direct read safe: the pool copies out under a cross-process
        read pin, segment mappings stay valid past unlink.  A miss
        (spilled, evicted, or produced on another node) falls through
        to the normal pull, which restores/transfers the copy.

        The map can
        race a spill/eviction in the window after the pull reply — a
        missing segment means re-pull (which restores), not data loss.
        A failed pull of an object WITH lineage is also retried: under
        node-kill chaos the node holding a just-reconstructed copy can
        die in the window between reconstruction and this pull, which
        must mean "reconstruct again", not "not reconstructable"
        (round-3 VERDICT weak #1 interleaving)."""
        if size_hint > 0:
            try:
                return self.store.get(oid, size_hint)
            except (FileNotFoundError, OSError):
                pass  # not local anymore: pull restores/transfers it
        for attempt in range(3):
            r = self.io.run(self._pull_with_recovery(oid, timeout))
            if not r.get("ok"):
                with self._refs_lock:
                    recoverable = oid in self._lineage
                if recoverable and attempt < 2:
                    continue
                raise ObjectLostError(oid.hex())
            try:
                return self.store.get(oid, r["size"])
            except FileNotFoundError:
                continue
        raise ObjectLostError(oid.hex())

    async def _pull_with_recovery(self, oid: ObjectID,
                                  timeout: Optional[float]) -> Dict:
        t = timeout if timeout is not None else 3600.0
        can_recover = oid in self._lineage
        r = await self._agent.call("pull_object", {
            "object_id": oid, "timeout": t, "fail_fast": can_recover})
        if r.get("ok") or not can_recover:
            return r
        if not await self._reconstruct_object(oid):
            return r
        return await self._agent.call("pull_object",
                                      {"object_id": oid, "timeout": t})

    async def _recover_lost_args(self, spec: TaskSpec) -> bool:
        """A pushed task failed with ObjectLostError: check its plane-ref
        args against the directory and reconstruct the missing ones we
        have lineage for.  Returns True if anything was recovered (the
        caller retries the push)."""
        recovered = False
        for arg in spec.args:
            if arg.kind != ArgKind.OBJECT_REF or arg.object_id is None:
                continue
            oid = arg.object_id
            if oid not in self._lineage:
                continue
            try:
                loc = await self._ctl.call("locate_object",
                                           {"object_id": oid})
            except RpcError:
                loc = None
            # The directory lags node death by the health-check window
            # (its "alive" filter is heartbeat-based), so a listed copy
            # may be on a node that is already gone — trusting it here
            # is exactly the round-3/4 interleaving that marked a
            # reconstructable object unreconstructable.  Confirm a
            # listed copy actually answers before believing it.
            confirmed = False
            for ent in (loc or {}).get("nodes") or []:
                try:
                    agent = await self._agent_for(ent["agent_addr"])
                    r = await asyncio.wait_for(
                        agent.call("objects_exist",
                                   {"object_ids": [oid]}), 3.0)
                    if r.get(oid):
                        confirmed = True
                        break
                except (RpcError, RemoteCallError,
                        asyncio.TimeoutError, OSError):
                    continue
            if confirmed:
                # A live copy exists: the executor's pull failure was
                # transient (e.g. raced a spill or the pull targeted a
                # dying node) — retrying the task is enough.
                recovered = True
                continue
            if not await self._reconstruct_object(oid):
                return False
            recovered = True
        return recovered

    async def _reconstruct_object(self, oid: ObjectID,
                                  depth: int = 0) -> bool:
        """Re-execute the task that created ``oid`` (ref:
        object_recovery_manager.h:38,90 — lineage reconstruction).  Upstream
        plane dependencies that are themselves gone are reconstructed
        first, depth-bounded.  Puts and actor-task results carry no
        lineage and surface ObjectLostError instead."""
        if depth > 8:
            return False
        spec = self._lineage.get(oid)
        if spec is None:
            return False
        inflight = self._reconstructing.get(oid)
        if inflight is not None:
            ok = await asyncio.shield(inflight)
            if ok:
                return True
            # The attempt we piggybacked on failed — its failure may
            # have been a transient interleaving (its target node died
            # mid-resubmit).  Fall through and attempt reconstruction
            # OURSELVES instead of propagating a possibly-stale False
            # (round-3 VERDICT weak #1).
            if self._reconstructing.get(oid) is not None:
                # Someone else already started the retry; join it.
                return await asyncio.shield(
                    self._reconstructing[oid])
        fut = asyncio.get_event_loop().create_future()
        self._reconstructing[oid] = fut
        ok = False
        try:
            for arg in spec.args:
                if arg.kind != ArgKind.OBJECT_REF or arg.object_id is None:
                    continue
                try:
                    loc = await self._ctl.call(
                        "locate_object", {"object_id": arg.object_id})
                except RpcError:
                    loc = None
                if not (loc and loc["nodes"]):
                    if not await self._reconstruct_object(arg.object_id,
                                                          depth + 1):
                        return False
            logger = __import__("logging").getLogger("ray_tpu")
            logger.warning("reconstructing lost object %s by re-executing "
                           "task %s", oid.hex()[:16], spec.display_name())
            self._mark_pending(spec.return_object_ids())
            await self._submit_normal(spec)
            got, val = self.memory.get_nowait(oid)
            ok = got and not isinstance(val, TaskError)
            return ok
        finally:
            self._reconstructing.pop(oid, None)
            if not fut.done():
                fut.set_result(ok)

    def get(self, refs: List[ObjectRef],
            timeout: Optional[float]) -> List[Any]:
        deadline = time.monotonic() + timeout if timeout is not None else None
        # Release this worker's lease CPU while blocked on ANY ref that
        # is not already local — including refs owned by OTHER processes
        # (ref: core_worker NotifyDirectCallTaskBlocked).  Scoping this
        # to our own pending returns deadlocks a fixed-size worker pool:
        # a task get()ing another owner's not-yet-produced object holds
        # its lease while the producing task queues behind it forever.
        needs_wait = []
        for r in refs:
            ok, _ = self.memory.get_nowait(r.id)
            if not ok:
                needs_wait.append(r.id)
        blocked = bool(needs_wait)
        if blocked:
            self._notify_blocked(True)
        try:
            if len(needs_wait) > 1:
                # One shared wait for the whole batch (see
                # MemoryStore.wait_for_many) — but only for refs whose
                # results arrive THROUGH the memory store (our own
                # pending returns); plane refs resolve via pulls below.
                with self._pending_lock:
                    batched = [o for o in needs_wait
                               if o in self._pending_returns]
                if len(batched) > 1:
                    remaining = (max(deadline - time.monotonic(), 0.0)
                                 if deadline is not None else None)
                    self.memory.wait_for_many(batched, remaining)
            out = []
            for r in refs:
                remaining = None
                if deadline is not None:
                    remaining = max(deadline - time.monotonic(), 0.0)
                with self._pending_lock:
                    pending = r.id in self._pending_returns
                if pending or self.memory.contains(r.id):
                    val = self.memory.wait_for(r.id, remaining)
                else:
                    val = self._fetch_store_value(r.id, remaining)
                if isinstance(val, _StoreRef):
                    val = self._fetch_store_value(r.id, remaining,
                                                  size_hint=val.size)
                if isinstance(val, TaskError):
                    raise val
                out.append(val)
            return out
        finally:
            if blocked:
                self._notify_blocked(False)
    # NOTE on _fetch_store_value for values we produced locally: the pull
    # is satisfied by the local directory lookup, no copy happens.

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float],
             fetch_local: bool) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        deadline = time.monotonic() + timeout if timeout is not None else None
        ready: List[ObjectRef] = []
        not_ready = list(refs)
        delay = 0.005
        while len(ready) < num_returns:
            progressed = False
            # Local checks first (memory store / owned-pending) — free.
            foreign: List[ObjectRef] = []
            for r in list(not_ready):
                ok, _ = self.memory.get_nowait(r.id)
                if ok:
                    ready.append(r)
                    not_ready.remove(r)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
                    continue
                with self._pending_lock:
                    if r.id not in self._pending_returns:
                        foreign.append(r)
            # Foreign refs: ONE bulk directory probe per pass instead of
            # two RPCs per ref per poll (round-1 weak item: O(refs x
            # polls) controller load from any wait loop).  The local
            # agent is the fallback source of truth for copies whose
            # controller publication failed or lagged.
            if foreign and len(ready) < num_returns:
                oids = [r.id for r in foreign]
                try:
                    res = self.io.run(self._ctl.call(
                        "locate_objects", {"object_ids": oids}),
                        timeout=5.0)
                except Exception:
                    res = {}
                missing = [o for o in oids if not res.get(o)]
                if missing:
                    try:
                        local = self.io.run(self._agent.call(
                            "objects_exist", {"object_ids": missing}),
                            timeout=5.0)
                        res = {**local, **{k: v for k, v in res.items()
                                           if v}}
                    except Exception:
                        pass
                for r in foreign:
                    if res.get(r.id):
                        ready.append(r)
                        if r in not_ready:
                            not_ready.remove(r)
                        progressed = True
                        if len(ready) >= num_returns:
                            break
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not progressed:
                time.sleep(delay)
                delay = min(delay * 1.5, 0.05)  # back off when idle
            else:
                delay = 0.005
        if fetch_local and ready:
            # Honour the caller's deadline during the fetch too: a timed
            # wait() must not block indefinitely pulling remote values
            # (round-2 weak item).  Refs whose fetch misses the deadline
            # are demoted back to not_ready — matching the reference's
            # contract that fetch_local readiness means "value is local".
            pending = list(ready)
            while pending:
                remaining = (max(0.0, deadline - time.monotonic())
                             if deadline is not None else None)
                try:
                    self.get(pending, timeout=remaining)
                    break
                except TaskError:
                    # The errored ref's value is now local (memory
                    # store); keep fetching the rest.  Resolved refs
                    # drop out, so each pass shrinks pending.
                    resident = self._locally_resident(pending)
                    nxt = [r for r in pending if r not in resident]
                    if len(nxt) == len(pending):
                        break  # defensive: no progress, stop looping
                    pending = nxt
                except GetTimeoutError:
                    resident = self._locally_resident(pending)
                    still_remote = [r for r in pending
                                    if r not in resident]
                    for r in still_remote:
                        ready.remove(r)
                    not_ready = still_remote + not_ready
                    break
        return ready, not_ready

    def _request_store_room(self, nbytes: int) -> None:
        """Seal-backpressure hook (any thread): ask the local agent to
        evict/spill ``nbytes`` of store headroom, synchronously."""
        if self._agent is None:
            return
        self.io.run(self._agent.call("make_room", {"bytes": nbytes}),
                    timeout=30.0)

    def _locally_resident(self, refs: List[ObjectRef]) -> set:
        """Subset of ``refs`` whose values are resident on this node
        (memory store — incl. error values — or local shm).  ONE
        batched agent probe for the rest, so callers stay O(1) RPCs."""
        resident = set()
        unknown: List[ObjectRef] = []
        for r in refs:
            ok, _ = self.memory.get_nowait(r.id)
            (resident.add if ok else unknown.append)(r)
        if unknown:
            try:
                res = self.io.run(self._agent.call(
                    "objects_exist",
                    {"object_ids": [r.id for r in unknown]}),
                    timeout=2.0)
                for r in unknown:
                    if res.get(r.id):
                        resident.add(r)
            except Exception:
                pass  # unreachable agent: treat as non-resident
        return resident

    def cancel(self, ref: ObjectRef, force: bool) -> None:
        """Cancel the task producing ``ref`` (ref: core_worker.cc
        CancelTask).  Queued lease requests are yanked from the agent;
        running tasks get TaskCancelledError raised in their executing
        thread (force=True kills the worker process instead).  Actor
        tasks are not cancellable (they would break call ordering) —
        a warning is emitted, matching the surfaced-gap contract."""
        sub = self._submissions.get(ref.id)
        if sub is None or sub.done:
            # Debug, not warning: bulk cancellation sweeps routinely
            # race completion by design (100k-queue benchmarks would
            # emit 100k log lines at warning level).
            logger.debug(
                "cancel(%s): no in-flight submission (already finished, "
                "unknown, or an actor task — not cancellable)", ref)
            return
        sub.cancelled = True
        sub.force = force
        self.io.call_soon(sub.cancel_event.set)
        try:
            self.io.run(self._cancel_inflight(sub), timeout=10.0)
        except Exception:
            pass  # flag checks in the submit loop still stop the task

    async def _cancel_inflight(self, sub: _Submission) -> None:
        if not sub.pushed:
            if sub.agent_addr is not None:
                agent = await self._agent_for(sub.agent_addr)
                await agent.call("cancel_lease_request",
                                 {"request_id": sub.request_id})
            return
        if sub.force:
            # Kill the worker process; the push RPC fails and the submit
            # loop reports TaskCancelledError (cancel flag suppresses
            # retries).
            agent = await self._agent_for(sub.agent_addr)
            await agent.call("kill_worker", {"worker_id": sub.worker_id})
        elif sub.worker_addr is not None:
            worker = await self._worker_client(sub.worker_addr)
            await worker.call("cancel_task",
                              {"task_id": sub.spec.task_id})

    # -------------------------------------------------------- introspection
    def cluster_resources(self) -> Dict[str, float]:
        nodes = self.io.run(self._ctl.call("list_nodes", {}))
        total: Dict[str, float] = {}
        for n in nodes:
            if n["alive"]:
                for k, v in n["resources"].items():
                    total[k] = total.get(k, 0.0) + v
        return total

    def available_resources(self) -> Dict[str, float]:
        nodes = self.io.run(self._ctl.call("list_nodes", {}))
        total: Dict[str, float] = {}
        for n in nodes:
            if n["alive"] and not n.get("draining"):
                # A draining node's capacity is leaving the cluster:
                # elastic gang sizing (ElasticScalingPolicy) must not
                # count chips that will be gone by the next attempt.
                for k, v in n["available"].items():
                    total[k] = total.get(k, 0.0) + v
        return total

    def nodes(self) -> List[Dict[str, Any]]:
        out = []
        for n in self.io.run(self._ctl.call("list_nodes", {})):
            out.append({
                "NodeID": n["node_id"].hex(), "Alive": n["alive"],
                "Resources": n["resources"], "AgentAddress": n["agent_addr"],
                "Labels": n["labels"], "IsHead": n.get("is_head", False),
                "Draining": n.get("draining", False),
                "DrainDeadline": n.get("drain_deadline", 0.0),
                "DrainReason": n.get("drain_reason", "")})
        return out

    def controller_call(self, method: str, payload=None, timeout=None):
        """Escape hatch used by util/state/collective layers."""
        return self.io.run(self._ctl.call(method, payload), timeout)

    def agent_call(self, method: str, payload=None, timeout=None):
        return self.io.run(self._agent.call(method, payload), timeout)

    # ------------------------------------------------------------ shutdown
    def shutdown(self) -> None:
        self._shutdown_flag = True
        try:
            # Give cached leases back so a departing driver doesn't pin
            # CPUs on a shared cluster until the keep-alive would expire.
            self.io.run(self._release_pooled_leases(), timeout=5.0)
        except Exception:
            pass
        if self._registered_job_int is not None and not self._owns_head:
            # A departing driver finishes its job so the controller
            # reaps its non-detached actors — a connect/disconnect
            # driver must not leak workers into the shared cluster.
            try:
                self.io.run(self._ctl.call(
                    "finish_job", {"job_id": self._registered_job_int}),
                    timeout=10.0)
            except Exception:
                pass
        try:
            if self._owns_head:
                try:
                    self.io.run(self._ctl.call("cluster_shutdown", {}),
                                timeout=5.0)
                except Exception:
                    pass
        finally:
            try:
                self._side_channel.close()
            except Exception:
                pass
            self.store.close()
            self.memory.clear()
            self.io.stop()
            for p in self._procs:
                try:
                    p.wait(timeout=3.0)
                except Exception:
                    try:
                        p.kill()
                    except Exception:
                        pass
            if self._owns_head:
                self._cleanup_shm()

    async def _release_pooled_leases(self) -> None:
        for st in list(self._sched_states.values()):
            for rid, agent_addr in list(st.request_agents.items()):
                self._cancel_lease_request_async(rid, agent_addr)
            for pl in list(st.leases.values()):
                try:
                    agent = await self._agent_for(pl.agent_addr)
                    await asyncio.wait_for(
                        agent.call("return_lease",
                                   {"lease_id": pl.lease_id}), 2.0)
                except Exception:
                    pass
            st.leases.clear()
            st.idle.clear()
        self._sched_states.clear()

    def _cleanup_shm(self) -> None:
        shm_dir = "/dev/shm"
        prefix = f"rt_{self.session}_"
        try:
            for name in os.listdir(shm_dir):
                if name.startswith(prefix):
                    try:
                        os.unlink(os.path.join(shm_dir, name))
                    except OSError:
                        pass
        except OSError:
            pass
