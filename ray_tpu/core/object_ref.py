"""ObjectRef — a future/handle for a value in the distributed object plane.

Role-equivalent to the reference's ObjectRef (ref: python/ray/_raylet.pyx
ObjectRef, src/ray/common/ray_object.h).  Holding a ref pins the value via
distributed reference counting; refs are awaitable through ``get``/``wait``
and may be passed as arguments to remote calls, which forwards the borrow.
"""

from __future__ import annotations

from typing import Optional

from .ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "_owner", "_in_band")

    def __init__(self, object_id: ObjectID, owner: str = "", in_band: bool = False):
        self.id = object_id
        self._owner = owner
        self._in_band = in_band  # True when created by local-mode put

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    def __reduce__(self):
        # Refs are routinely pickled into task args; the receiving runtime
        # re-registers the borrow on deserialization (see worker context).
        return (ObjectRef, (self.id, self._owner, self._in_band))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import runtime

        return runtime.get_runtime().as_future(self)

    def __await__(self):
        from . import runtime

        return runtime.get_runtime().await_ref(self).__await__()


class ActorHandleRef:
    """Marker wrapper used when an actor handle travels inside args."""

    __slots__ = ("state",)

    def __init__(self, state):
        self.state = state
