"""ObjectRef — a future/handle for a value in the distributed object plane.

Role-equivalent to the reference's ObjectRef (ref: python/ray/_raylet.pyx
ObjectRef, src/ray/common/ray_object.h).  Holding a ref pins the value via
distributed reference counting; refs are awaitable through ``get``/``wait``
and may be passed as arguments to remote calls, which forwards the borrow.
"""

from __future__ import annotations

import threading
from typing import Optional

from .ids import ObjectID


class _RefCollector(threading.local):
    """Collects ObjectRef ids encountered while pickling a value.

    Activated by the worker around result serialization so refs embedded
    in a return value can be protected (borrow registration) before the
    producing frame's own references die — the ownership-handoff window
    (ref: reference_count.h borrowed-refs protocol)."""

    def __init__(self):
        self.active: Optional[list] = None


_collector = _RefCollector()


def collect_embedded_refs():
    """Context manager: activates collection, yields the id list."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        prev = _collector.active
        _collector.active = found = []
        try:
            yield found
        finally:
            _collector.active = prev

    return _cm()


class ObjectRef:
    __slots__ = ("id", "_owner", "_in_band", "_counted")

    def __init__(self, object_id: ObjectID, owner: str = "",
                 in_band: bool = False, counted: bool = True):
        self.id = object_id
        self._owner = owner
        self._in_band = in_band  # True when created by local-mode put
        self._counted = counted  # False for internal transient handles
        if not counted:
            return
        from . import runtime

        rt = runtime.get_runtime_quiet()
        if rt is not None:
            rt.add_local_ref(object_id)

    def __del__(self):
        # Lifecycle hook feeding distributed ref counting (ref:
        # reference_count.h RemoveLocalReference).  Must never raise:
        # __del__ can fire during interpreter teardown.
        try:
            if not self._counted:
                return
            from . import runtime

            rt = runtime.get_runtime_quiet()
            if rt is not None:
                rt.remove_local_ref(self.id)
        except Exception:
            pass

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    def __reduce__(self):
        # Refs are routinely pickled into task args; the receiving runtime
        # re-registers the borrow on deserialization (see worker context).
        if _collector.active is not None:
            _collector.active.append(self.id)
        return (ObjectRef, (self.id, self._owner, self._in_band))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import runtime

        return runtime.get_runtime().as_future(self)

    def __await__(self):
        from . import runtime

        return runtime.get_runtime().await_ref(self).__await__()


class ActorHandleRef:
    """Marker wrapper used when an actor handle travels inside args."""

    __slots__ = ("state",)

    def __init__(self, state):
        self.state = state
