"""ObjectRef — a future/handle for a value in the distributed object plane.

Role-equivalent to the reference's ObjectRef (ref: python/ray/_raylet.pyx
ObjectRef, src/ray/common/ray_object.h).  Holding a ref pins the value via
distributed reference counting; refs are awaitable through ``get``/``wait``
and may be passed as arguments to remote calls, which forwards the borrow.
"""

from __future__ import annotations

import threading
from typing import Optional

from .ids import ObjectID


class _RefCollector(threading.local):
    """Collects ObjectRef ids encountered while pickling a value.

    Activated by the worker around result serialization so refs embedded
    in a return value can be protected (borrow registration) before the
    producing frame's own references die — the ownership-handoff window
    (ref: reference_count.h borrowed-refs protocol)."""

    def __init__(self):
        self.active: Optional[list] = None


_collector = _RefCollector()


def collect_embedded_refs():
    """Context manager: activates collection, yields the id list."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        prev = _collector.active
        _collector.active = found = []
        try:
            yield found
        finally:
            _collector.active = prev

    return _cm()


class ObjectRef:
    __slots__ = ("id", "_owner", "_in_band", "_counted", "_gen")

    def __init__(self, object_id: ObjectID, owner: str = "",
                 in_band: bool = False, counted: bool = True):
        self.id = object_id
        self._owner = owner
        self._in_band = in_band  # True when created by local-mode put
        self._counted = counted  # False for internal transient handles
        if not counted:
            self._gen = -1
            return
        from . import runtime

        # Runtime GENERATION stamp: id counters reset across
        # shutdown()/init() in one process, so a stale ref GC'd after
        # a re-init must not decrement a COLLIDING id's refcount on
        # the new runtime.
        self._gen = runtime.current_generation()
        rt = runtime.get_runtime_quiet()
        if rt is not None:
            rt.add_local_ref(object_id)

    def __del__(self):
        # Lifecycle hook feeding distributed ref counting (ref:
        # reference_count.h RemoveLocalReference).  Must never raise:
        # __del__ can fire during interpreter teardown.
        try:
            if not self._counted:
                return
            from . import runtime

            if runtime.current_generation() != self._gen:
                return  # born under a previous runtime generation
            rt = runtime.get_runtime_quiet()
            if rt is not None:
                rt.remove_local_ref(self.id)
        except Exception:
            pass

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    def __reduce__(self):
        # Refs are routinely pickled into task args; the receiving runtime
        # re-registers the borrow on deserialization (see worker context).
        if _collector.active is not None:
            _collector.active.append(self.id)
        # A pickled ref can reach another process and grow borrowers:
        # it is no longer eligible for the owner's eager local free
        # (cluster_runtime._release_object fast path).
        from . import runtime

        rt = runtime.get_runtime_quiet()
        if rt is not None:
            mark = getattr(rt, "mark_ref_escaped", None)
            if mark is not None:
                mark(self.id)
        return (ObjectRef, (self.id, self._owner, self._in_band))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import runtime

        return runtime.get_runtime().as_future(self)

    def __await__(self):
        from . import runtime

        return runtime.get_runtime().await_ref(self).__await__()


class ActorHandleRef:
    """Marker wrapper used when an actor handle travels inside args."""

    __slots__ = ("state",)

    def __init__(self, state):
        self.state = state


class ObjectRefGenerator:
    """Iterator over the ObjectRefs a streaming task yields (ref:
    python/ray/_raylet.pyx:284 ObjectRefGenerator /
    num_returns="streaming").  ``next()`` blocks until the executor
    reports the next item (or the task completes), returns its
    ObjectRef, and acks consumption so the executor's backpressure
    window advances.  A mid-generator exception is delivered as one
    final ref whose ``get`` raises, then StopIteration — matching the
    reference's error-object semantics.  Async iteration offloads the
    blocking wait to the default executor.
    """

    def __init__(self, task_id, sentinel_id: ObjectID,
                 owner_runtime=None):
        self.task_id = task_id
        # Submission bookkeeping (cancel, pending) anchors on the
        # sentinel id; expose it as .id so ray_tpu.cancel(gen) works.
        self.id = sentinel_id
        self._closed = False
        # Bind to the OWNING runtime (weakly): task-id counters reset
        # across shutdown()/init() generations inside one process, so
        # a stale generator used after a re-init must not touch a
        # COLLIDING id's live stream on the new runtime (observed as
        # a vanishing actor stream whenever test ordering realigned
        # the counters).  The owner is passed explicitly by the
        # submitting runtime.
        import weakref

        self._rt_ref = (weakref.ref(owner_runtime)
                        if owner_runtime is not None else None)

    # ------------------------------------------------------ sync iterator
    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        return self._next_ref(timeout=None)

    def _next_ref(self, timeout) -> "ObjectRef":
        import time as _time

        from . import runtime as _runtime
        from .errors import GetTimeoutError

        rt = self._rt_ref() if self._rt_ref is not None else None
        if rt is None or rt is not _runtime.get_runtime_quiet():
            # Owning runtime gone or superseded: the stream died with
            # it; never touch a colliding id's state on a newer one.
            raise StopIteration
        st = rt._streams.get(self.task_id.hex())
        if st is None:
            raise StopIteration
        deadline = (_time.monotonic() + timeout
                    if timeout is not None else None)
        while True:
            with st.lock:
                if st.ready:
                    oid = st.ready.popleft()
                    st.consumed += 1
                    consumed = st.consumed
                    worker = st.worker_addr
                    ref = ObjectRef(oid)
                    rt.stream_ack(self.task_id, consumed, worker)
                    return ref
                if st.done:
                    if st.error is None and st.total is not None \
                            and st.consumed < st.total:
                        # The producer reported N items but fewer
                        # arrived (a dropped connection can lose
                        # in-flight notifies): surface loss, never a
                        # silently short stream.
                        from .errors import ObjectLostError

                        st.error = ObjectLostError(
                            f"stream lost items "
                            f"{st.consumed + 1}..{st.total} of "
                            f"{self.task_id.hex()[:16]} in transit")
                    if st.error is not None and not st.error_delivered:
                        # Deliver the failure as one final item ref.
                        st.error_delivered = True
                        from .ids import ObjectID as _OID

                        oid = _OID.for_task_return(self.task_id,
                                                   st.produced + 1)
                        rt._stream_put_error(oid, st.error)
                        return ObjectRef(oid)
                    rt._streams.pop(self.task_id.hex(), None)
                    raise StopIteration
                st.event.clear()
            remaining = None
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise GetTimeoutError(
                        f"no stream item within {timeout}s")
            st.event.wait(remaining if remaining is not None else 1.0)

    # ----------------------------------------------------- async iterator
    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        import asyncio

        loop = asyncio.get_event_loop()
        done = object()

        def _safe_next():
            # StopIteration must not cross the executor boundary:
            # asyncio.Future.set_exception rejects it (PEP 479
            # interaction), which would kill the awaiting coroutine
            # with a TypeError instead of ending the iteration.
            try:
                return self.__next__()
            except StopIteration:
                return done

        item = await loop.run_in_executor(None, _safe_next)
        if item is done:
            raise StopAsyncIteration
        return item

    def close(self) -> None:
        """Release owner-side stream state; cancels a still-running
        producer (an abandoned unbounded stream must not spin in its
        backpressure wait forever)."""
        if self._closed:
            return
        self._closed = True
        from . import runtime as _runtime

        rt = _runtime.get_runtime_quiet()
        owner = self._rt_ref() if self._rt_ref is not None else None
        if rt is not None and rt is owner:
            try:
                rt._stream_close(self.task_id)
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # interpreter teardown

    def __repr__(self):
        return f"ObjectRefGenerator({self.task_id.hex()[:12]})"
