"""Runtime context: the per-process face of the framework.

Role-equivalent to the reference's CoreWorker + worker.py global state (ref:
src/ray/core_worker/core_worker.h:166, python/ray/_private/worker.py).  A
Runtime owns ID derivation (task counters per parent context), and the
backend implementation of submit/get/put/wait.  Two backends exist:
LocalRuntime (in-process, synchronous — the reference's local_mode) and
ClusterRuntime (multiprocess controller/agent/worker tree).
"""

from __future__ import annotations

import abc
import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from .config import RuntimeConfig
from .ids import ActorID, JobID, TaskID, _Counter
from .object_ref import ObjectRef
from .task import TaskSpec

_global_lock = threading.Lock()
_global_runtime: Optional["BaseRuntime"] = None
# Monotonic runtime GENERATION: bumps on every set_runtime.  Id
# counters (task/put) reset across shutdown()/init() inside one
# process, so ids COLLIDE across generations — lifecycle hooks of
# refs born under an older generation must become no-ops instead of
# mutating a colliding id's state on the new runtime.
_generation = 0


def current_generation() -> int:
    return _generation


def get_runtime() -> "BaseRuntime":
    rt = _global_runtime
    if rt is None:
        raise RuntimeError(
            "ray_tpu.init() has not been called in this process.")
    return rt


def get_runtime_quiet() -> Optional["BaseRuntime"]:
    """Like get_runtime but returns None when uninitialized — used by
    ObjectRef lifecycle hooks, which must never raise (they run in
    __init__/__del__, including during unpickling in processes that have
    no runtime, e.g. the controller)."""
    return _global_runtime


def is_initialized() -> bool:
    return _global_runtime is not None


def set_runtime(rt: Optional["BaseRuntime"]) -> None:
    global _global_runtime, _generation
    with _global_lock:
        _global_runtime = rt
        _generation += 1


class _TaskContext(threading.local):
    """Tracks the currently-executing task for child-ID derivation."""

    def __init__(self):
        self.current_task_id: Optional[TaskID] = None


class BaseRuntime(abc.ABC):
    def __init__(self, config: RuntimeConfig, job_id: Optional[JobID] = None):
        self.config = config
        self.job_id = job_id or JobID.from_int(1)
        self._driver_task_id = TaskID.for_driver(self.job_id)
        self._ctx = _TaskContext()
        self._task_counter = _Counter()
        self._actor_counter = _Counter()
        self._put_counter = _Counter()
        self._actor_seq: Dict[ActorID, _Counter] = {}
        self._seq_lock = threading.Lock()
        # Set by the worker when this process hosts an actor instance
        # (read through api.get_runtime_context, ref:
        # runtime_context.py get_actor_id).
        self.current_actor_id: Optional[ActorID] = None

    # -- ID derivation ------------------------------------------------------
    def current_task_id(self) -> TaskID:
        return self._ctx.current_task_id or self._driver_task_id

    def set_current_task(self, task_id: Optional[TaskID]) -> None:
        self._ctx.current_task_id = task_id

    def next_task_id(self) -> TaskID:
        return TaskID.of(self.job_id, self.current_task_id(),
                         self._task_counter.next())

    def next_actor_id(self) -> ActorID:
        return ActorID.of(self.job_id, self.current_task_id(),
                          self._actor_counter.next())

    def actor_creation_task_id(self, actor_id: ActorID) -> TaskID:
        return TaskID.for_actor_creation(actor_id)

    def next_actor_task_id(self, actor_id: ActorID) -> TaskID:
        # Actor-task IDs derive from the *caller's* context, not (actor, seq):
        # two independent submitters each start their per-actor seq at 1, so a
        # seq-derived ID would collide across callers.
        del actor_id
        return self.next_task_id()

    def next_actor_seq(self, actor_id: ActorID) -> int:
        with self._seq_lock:
            c = self._actor_seq.get(actor_id)
            if c is None:
                c = self._actor_seq[actor_id] = _Counter()
        return c.next()

    def next_put_index(self) -> int:
        return self._put_counter.next()

    # -- Backend interface --------------------------------------------------
    @abc.abstractmethod
    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]: ...

    @abc.abstractmethod
    def create_actor(self, spec: TaskSpec) -> None: ...

    @abc.abstractmethod
    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]: ...

    @abc.abstractmethod
    def put(self, value: Any) -> ObjectRef: ...

    @abc.abstractmethod
    def get(self, refs: List[ObjectRef],
            timeout: Optional[float]) -> List[Any]: ...

    @abc.abstractmethod
    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float],
             fetch_local: bool) -> Tuple[List[ObjectRef], List[ObjectRef]]: ...

    @abc.abstractmethod
    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None: ...

    @abc.abstractmethod
    def get_named_actor(self, name: str, namespace: str = ""): ...

    def cancel(self, ref: ObjectRef, force: bool) -> None:
        raise NotImplementedError

    # -- Reference counting hooks (ref: reference_count.h:66) ---------------
    # No-ops by default; ClusterRuntime implements distributed counting.
    def add_local_ref(self, object_id) -> None:
        pass

    def remove_local_ref(self, object_id) -> None:
        pass

    @abc.abstractmethod
    def shutdown(self) -> None: ...

    # -- Introspection ------------------------------------------------------
    def cluster_resources(self) -> Dict[str, float]:
        return {}

    def available_resources(self) -> Dict[str, float]:
        return {}

    def nodes(self) -> List[Dict[str, Any]]:
        return []

    # -- Async adapters -----------------------------------------------------
    def as_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self.get([ref], None)[0])
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    async def await_ref(self, ref: ObjectRef):
        import asyncio

        return await asyncio.wrap_future(self.as_future(ref))
