"""Runtime configuration flag table.

Equivalent in role to the reference's RAY_CONFIG X-macro table (ref:
src/ray/common/ray_config_def.h), rebuilt as a typed Python registry: every
flag has a name, type, default, and doc; every flag is overridable via the
``RT_<NAME>`` environment variable so cluster-wide propagation is just env
inheritance.  A frozen snapshot is attached to each session and shipped to
every spawned process.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict

_ENV_PREFIX = "RT_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


@dataclass
class _Flag:
    name: str
    type: type
    default: Any
    doc: str


_REGISTRY: Dict[str, _Flag] = {}


def define_flag(name: str, type_: type, default: Any, doc: str = "") -> None:
    _REGISTRY[name] = _Flag(name, type_, default, doc)


# ---------------------------------------------------------------------------
# Core runtime flags (ref counterpart: ray_config_def.h flag table).
# ---------------------------------------------------------------------------
define_flag("raylet_heartbeat_period_ms", int, 1000,
            "Node agent -> controller liveness report period.")
define_flag("health_check_failure_threshold", int, 5,
            "Missed heartbeats before a node is marked dead.")
define_flag("task_retry_delay_ms", int, 100,
            "Delay before resubmitting a failed retriable task.")
define_flag("max_task_retries", int, 3,
            "Default retry budget for retriable normal tasks.")
define_flag("max_actor_restarts", int, 0,
            "Default actor restart budget (0 = no restart).")
define_flag("object_store_memory_bytes", int, 2 * 1024**3,
            "Per-node shared-memory object store capacity.")
define_flag("object_inline_max_bytes", int, 100 * 1024,
            "Objects at or below this size are inlined in control messages "
            "instead of the shared-memory plane.")
define_flag("arg_pull_timeout_s", float, 60.0,
            "Executor-side bound on pulling one task argument; expiry "
            "surfaces ObjectLostError so the owner can reconstruct from "
            "lineage and retry instead of hanging.")
define_flag("worker_pool_min_workers", int, 0,
            "Pre-started idle workers per node.")
define_flag("worker_pool_max_workers", int, 0,
            "Max concurrent workers per node (0 = #CPUs).")
define_flag("worker_prestart", int, -1,
            "Warm-worker prestart pool target per node: the agent "
            "keeps this many idle workers pre-spawned (per runtime-"
            "env hash) so actor/task creation ADOPTS a live process "
            "instead of paying a full interpreter spawn (ref: "
            "worker_pool.h:216 PopWorker).  -1 = node CPU count; "
            "0 disables prestarting.")
define_flag("worker_prestart_refill_ms", int, 200,
            "Prestart pool refill cadence: the pool is also refilled "
            "immediately after every adoption; this periodic tick "
            "heals losses (worker death, env churn).")
define_flag("worker_prestart_burst", int, 0,
            "Spawn-storm hysteresis: max worker processes concurrently "
            "forked-but-unregistered by the prestart refill (bounds "
            "the fork herd on small hosts).  0 = max(2, node CPUs).")
define_flag("worker_prestart_env_ttl_s", float, 60.0,
            "How long a non-default runtime-env hash stays warm (the "
            "pool keeps prestarted workers for env hashes adopted "
            "within this window; the default env is always warm).")
define_flag("worker_idle_timeout_s", float, 60.0,
            "Idle worker reap timeout.")
define_flag("worker_start_timeout_s", float, 60.0,
            "Time allowed for a worker process to register before failing.")
define_flag("scheduler_spread_threshold", float, 0.5,
            "Hybrid policy: utilization below which tasks pack onto the "
            "local node before spilling (ref: hybrid_scheduling_policy.h).")
define_flag("scheduler_top_k_fraction", float, 0.2,
            "Hybrid policy: random choice among the best k fraction of nodes.")
define_flag("lineage_max_bytes", int, 64 * 1024**2,
            "Cap on pinned lineage used for object reconstruction.")
define_flag("rpc_connect_timeout_s", float, 30.0, "RPC dial timeout.")
define_flag("rpc_request_timeout_s", float, 0.0,
            "Default RPC deadline (0 = none).")
define_flag("log_to_driver", bool, True,
            "Stream worker stdout/stderr back to the driver.")
define_flag("session_dir_root", str, "/tmp/ray_tpu",
            "Root directory for per-session state (sockets, logs, store).")
define_flag("shm_dir", str, "/dev/shm",
            "Directory backing the shared-memory object plane.")
define_flag("metrics_report_period_s", float, 5.0,
            "Stats export period from workers/agents.")
define_flag("task_event_buffer_size", int, 10000,
            "Max buffered per-task lifecycle events before drop-oldest.")
define_flag("tracing_enabled", bool, False, "Emit task/actor spans.")
define_flag("log_to_driver", bool, True,
            "Tail worker stdout/stderr on each node agent and stream "
            "the lines to the submitting driver's console (ref: "
            "_private/log_monitor.py).")
define_flag("memory_usage_threshold", float, 0.95,
            "Host memory-usage fraction above which the OOM monitor "
            "kills workers running retriable work.")
define_flag("memory_monitor_refresh_ms", int, 1000,
            "OOM monitor sampling period; 0 disables the monitor.")
define_flag("controller_persistence_enabled", bool, True,
            "Snapshot controller tables to the session dir so a "
            "restarted controller resumes (GCS fault tolerance). "
            "Default-on: matches the reference running GCS over a "
            "persistent store (ref: gcs_server.h:113 StorageType).")
define_flag("controller_reconnect_grace_s", float, 30.0,
            "How long agents tolerate an unreachable controller "
            "(reconnect window across a controller restart) before "
            "shutting the node down.")
define_flag("object_transfer_chunk_bytes", int, 4 * 1024**2,
            "Node-to-node object transfer chunk size; larger objects "
            "move as a sequence of chunk RPCs, not one giant frame.")
define_flag("pull_parallelism", int, 8,
            "Max concurrent chunk-fetch RPCs per chunked object pull. "
            "A pull larger than object_transfer_chunk_bytes issues up "
            "to this many fetch_chunk requests in flight (bounded "
            "window = transfer backpressure); the source overlaps its "
            "per-chunk store/disk reads with the wire, so large-block "
            "ingest approaches line rate instead of one-chunk-per-RTT.")
define_flag("object_store_backend", str, "pool",
            "Node object store backing: 'pool' (native C++ slab "
            "allocator over one shm region, src/shm_pool.cpp — the "
            "production path, like the reference's plasma slab; falls "
            "back to segments if the toolchain is missing) or "
            "'segments' (one shm segment per object).")
define_flag("object_spill_enabled", bool, True,
            "Spill pinned objects to disk under store pressure instead "
            "of running over capacity.")
define_flag("autoscaling_enabled", bool, False,
            "Hold cluster-infeasible lease requests (reported as demand "
            "for the autoscaler to satisfy) instead of failing fast.")
define_flag("runtime_env_cache_bytes", int, 2 * 1024**3,
            "LRU cap on runtime-env package blobs held in controller "
            "memory; least-recently-used packages are evicted beyond it.")
define_flag("actor_ready_timeout_s", float, 120.0,
            "How long callers wait for a PENDING/RESTARTING actor to "
            "become ALIVE before failing the call (many concurrent "
            "actor creations on a loaded host need more than the "
            "default).")
define_flag("lease_keepalive_s", float, 0.5,
            "How long an owner keeps a granted-but-idle worker lease "
            "cached for reuse by the next same-shaped task before "
            "returning it to the node agent (ref: "
            "normal_task_submitter.h:74 lease_timeout_ms_ — lease "
            "reuse removes the per-task lease round-trip).")
define_flag("lease_pipeline_depth", int, 8,
            "In-flight task pushes per leased worker (ref: pipelining "
            "in normal_task_submitter.h).  The worker executes one at "
            "a time from an explicit queue and RETURNS queued tasks "
            "when its running task blocks in get(), so depth > 1 "
            "cannot deadlock nested tasks.")
define_flag("lease_pipeline_grace_ms", int, 25,
            "How long a queued task waits for a FRESH lease before it "
            "may pipeline behind a busy leased worker — preserves "
            "parallelism for long tasks (new workers claim young "
            "items) while a saturated queue still pipelines deep.")
define_flag("lease_request_limit", int, 10,
            "Max concurrent outstanding lease requests per scheduling "
            "key (resource shape + runtime env) per owner (ref: "
            "StaticLeaseRequestRateLimiter in "
            "normal_task_submitter.h).")
define_flag("streaming_max_pending", int, 0,
            "Executor-side backpressure window for streaming "
            "generators: max unconsumed items before the producer "
            "pauses (0 = unbounded, matching the reference default). "
            "A bounded pause is treated as a blocked state, so tasks "
            "pipelined behind the paused producer requeue to another "
            "worker instead of stalling forever.")
define_flag("result_redelivery_timeout_s", float, 30.0,
            "How long a worker retains task/stream results it could "
            "not deliver (owner connection mid-reregistration), "
            "retrying whenever the owner's tag re-registers, before "
            "dropping them.")
define_flag("reply_redelivery_grace_s", float, 10.0,
            "Owner-side wait for a redelivered actor-call reply after "
            "the worker connection dropped: the owner re-dials (which "
            "re-registers its tag, triggering the worker's "
            "redelivery) and only fails the call once this grace "
            "expires.")
define_flag("collective_watchdog_s", float, 30.0,
            "Gang watchdog deadline: a collective some ranks entered "
            "but others have not joined within this window is flagged "
            "hung by `rt doctor` (names the op and the missing "
            "ranks).")
define_flag("dist_init_timeout_s", float, 120.0,
            "Distributed-init watchdog deadline: a gang where some "
            "ranks entered the jax.distributed mesh rendezvous but "
            "the barrier has not closed within this window gets an "
            "`rt doctor` finding naming the missing ranks.  Longer "
            "than the collective watchdog because a cold rendezvous "
            "legitimately waits on worker scheduling.")
define_flag("stuck_task_min_s", float, 60.0,
            "Stuck-task detector floor: a RUNNING task is never "
            "flagged before this age, and a task stuck in owner-side "
            "scheduling (queued/lease-requested with no progress) is "
            "flagged after it.")
define_flag("stuck_task_p99_factor", float, 3.0,
            "Stuck-task detector multiplier: a RUNNING task is "
            "flagged once its age exceeds factor x the historical p99 "
            "duration of same-named finished tasks (and the floor).")
define_flag("preemption_grace_s", float, 30.0,
            "Drain window granted on a preemption notice (SIGTERM / "
            "`rt drain`): the node agent stops accepting leases, "
            "reports a drain deadline this far in the future, and the "
            "training plane races a checkpoint-on-notice against it "
            "(GCP spot TPUs deliver ~30s between notice and VM "
            "death).")
define_flag("restart_backoff_base_s", float, 1.0,
            "First inter-attempt delay of the train controller's "
            "jittered exponential restart backoff (0 disables "
            "backoff — the pre-drain-plane hot-loop retry).")
define_flag("restart_backoff_max_s", float, 60.0,
            "Ceiling on the train restart backoff delay.")
define_flag("restart_backoff_multiplier", float, 2.0,
            "Growth factor between consecutive restart delays.")
define_flag("restart_backoff_jitter", float, 0.2,
            "Fractional jitter on each restart delay (0.2 = +/-20%), "
            "decorrelating gang restarts across drivers after a "
            "fleet-wide preemption wave.")
define_flag("job_preemption_enabled", bool, True,
            "Let a high-priority gang that cannot place preempt a "
            "strictly-lower-priority job's gang through the drain/"
            "checkpoint-on-notice path (the victim restarts from its "
            "notice checkpoint without burning max_failures).")
define_flag("preempt_pending_s", float, 2.0,
            "How long a high-priority gang must sit unplaceable before "
            "the controller selects a preemption victim — a short "
            "damper so capacity about to free naturally (a finishing "
            "gang, a joining node) is not bought with a kill.")
define_flag("starvation_warn_s", float, 60.0,
            "Doctor threshold: a gang/lease request pending longer "
            "than this yields a starved-job finding naming the job, "
            "its priority, and the jobs holding the contested "
            "resources (critical when the starved job outranks every "
            "holder).")
define_flag("serve_request_timeout_s", float, 60.0,
            "Default end-to-end deadline for one serve request "
            "(proxy -> replica, spanning every failover retry).  The "
            "ingress maps expiry to HTTP 504 / gRPC DEADLINE_EXCEEDED; "
            "per-request override via the X-RT-Timeout-S header (HTTP) "
            "or the timeout_s request field (gRPC).  0 = no deadline.")
define_flag("serve_max_retries", int, 3,
            "Transparent failover budget for a serve request that "
            "fails with a SYSTEM fault (replica/worker death, lost "
            "object) — the router re-routes it to a different healthy "
            "replica within the request deadline.  User exceptions "
            "are never retried.")
define_flag("serve_max_queued", int, 100,
            "Per-deployment admission queue bound at each handle/"
            "ingress: requests beyond the replicas' concurrent "
            "capacity wait here; when full the OLDEST queued request "
            "is shed with HTTP 429 / gRPC RESOURCE_EXHAUSTED instead "
            "of letting every request time out.  0 disables admission "
            "control (dispatch-immediately).")
define_flag("serve_breaker_failures", int, 3,
            "Consecutive system-fault failures that trip a replica's "
            "circuit breaker OPEN: the router stops sending it "
            "traffic before the controller's health probe notices a "
            "black-holed replica.")
define_flag("serve_breaker_reset_s", float, 2.0,
            "Base delay before an OPEN replica breaker admits one "
            "half-open probe request; repeated trips back off "
            "exponentially with jitter (the PR-4 RestartBackoff "
            "schedule, capped at 30s).")
define_flag("straggler_threshold", float, 0.2,
            "Straggler detector: a rank whose step time exceeds the "
            "per-step median by this fraction, sustained over the "
            "sliding window of recent steps, is flagged.")
define_flag("hotpath_sample", int, 64,
            "Control-plane hot-path introspection sampling stride: "
            "1 in N submitted tasks carries a phase-stamp vector "
            "(owner submit -> lease -> exec -> reply) aggregated "
            "behind `rt hotpath`.  1 = every task, 0 disables.")
# TPU-specific flags.
define_flag("tpu_chips_per_host", int, 0,
            "Override detected TPU chip count (0 = autodetect).")
define_flag("tpu_visible_chips_env", str, "TPU_VISIBLE_CHIPS",
            "Env var used to isolate TPU chips per worker, the TPU analogue "
            "of CUDA_VISIBLE_DEVICES (ref: _private/accelerators/tpu.py).")


@dataclass
class RuntimeConfig:
    """Immutable-ish snapshot of all flags for one session."""

    values: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_env(cls, overrides: Dict[str, Any] | None = None) -> "RuntimeConfig":
        values = {}
        for name, flag in _REGISTRY.items():
            raw = os.environ.get(_ENV_PREFIX + name.upper())
            if raw is not None:
                values[name] = _PARSERS[flag.type](raw)
            else:
                values[name] = flag.default
        if overrides:
            for k, v in overrides.items():
                if k not in _REGISTRY:
                    raise KeyError(f"Unknown config flag: {k}")
                values[k] = v
        return cls(values)

    def __getattr__(self, name: str):
        try:
            return self.values[name]
        except KeyError:
            raise AttributeError(name) from None

    def to_json(self) -> str:
        return json.dumps(self.values)

    @classmethod
    def from_json(cls, s: str) -> "RuntimeConfig":
        return cls(json.loads(s))

    def env_overrides(self) -> Dict[str, str]:
        """Env vars that reproduce this config in a child process."""
        out = {}
        for name, value in self.values.items():
            default = _REGISTRY[name].default
            if value != default:
                out[_ENV_PREFIX + name.upper()] = str(value)
        return out


def flags() -> Dict[str, _Flag]:
    return dict(_REGISTRY)
