"""Worker process entry point — executes tasks and hosts actors.

Role-equivalent to the reference's default_worker.py + the execution half
of CoreWorker (ref: python/ray/_private/workers/default_worker.py, task
execution handler _raylet.pyx:2244, TaskReceiver + ActorSchedulingQueue in
src/ray/core_worker/transport/task_receiver.h).  The worker registers with
its node agent, serves direct task pushes from owners, and on actor
creation becomes that actor's dedicated process with per-caller ordered
method queues, a thread pool honoring ``max_concurrency``, and native
asyncio execution for coroutine methods.

TPU isolation: chip ids granted with the lease are exported as
``TPU_VISIBLE_CHIPS`` *before* any user code imports jax, the analogue of
the reference's per-worker CUDA_VISIBLE_DEVICES handling (ref:
python/ray/_private/accelerators/tpu.py TPU_VISIBLE_CHIPS).
"""

from __future__ import annotations

import os as _os_early
import time as _time_early

# Startup-phase anchors (rt_worker_startup_seconds): the agent stamps
# RT_SPAWN_TS at fork; everything between it and this line is the
# "spawn" phase (fork + interpreter boot + site), everything from here
# to the end of this module's import is the "import" phase.  These two
# lines must stay ABOVE the heavy imports to measure them.
_SPAWN_TS = float(_os_early.environ.get("RT_SPAWN_TS") or 0.0)
_IMPORT_T0 = _time_early.time()

import asyncio  # noqa: E402
import faulthandler  # noqa: E402
import inspect  # noqa: E402
import logging  # noqa: E402
import os  # noqa: E402
import signal as _signal  # noqa: E402
import sys  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402
import traceback  # noqa: E402
from concurrent.futures import ThreadPoolExecutor  # noqa: E402
from typing import Any, Dict, List, Optional, Tuple  # noqa: E402

# NOTE: cloudpickle (via serialization/rpc lazy accessors), jax,
# telemetry, and the collective stack are imported lazily at first
# use — a prestarted pool worker must be cheap to fork, and most
# workers never touch most of that stack until their first frame.
from . import runtime as runtime_mod  # noqa: E402
from . import serialization  # noqa: E402
from .cluster_runtime import ClusterRuntime  # noqa: E402
from .config import RuntimeConfig  # noqa: E402
from .errors import ActorError, TaskCancelledError, TaskError  # noqa: E402
from .ids import ActorID, JobID, WorkerID  # noqa: E402
from .rpc import RpcClient, RpcError, RpcServer, spawn_task  # noqa: E402
from .task import ArgKind, TaskResult, TaskSpec  # noqa: E402
from ..util import hotpath  # noqa: E402  (stdlib-only; stamp slots)

_IMPORT_DONE = _time_early.time()

logger = logging.getLogger("ray_tpu.worker")


class Worker:
    def __init__(self):
        self.session = os.environ["RT_SESSION_NAME"]
        self.controller_addr = os.environ["RT_CONTROLLER_ADDR"]
        self.agent_addr = os.environ["RT_AGENT_ADDR"]
        self.node_id_hex = os.environ["RT_NODE_ID"]
        self.config = RuntimeConfig.from_env()
        self.worker_id = WorkerID.from_random()
        self.server = RpcServer()
        self.runtime: Optional[ClusterRuntime] = None
        self._func_cache: Dict[str, Any] = {}
        self._task_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task-exec")
        # Actor state.
        self.actor_id: Optional[ActorID] = None
        self.actor_instance: Any = None
        self.actor_executor: Optional[ThreadPoolExecutor] = None
        self.actor_lock = threading.Lock()
        self._exit_event = asyncio.Event()
        # Cancellation state: ids cancelled before execution started
        # (bounded FIFO — a cancel that never matches a push must not
        # accumulate forever), and the (task_id, thread ident) currently
        # running in _task_executor.
        from collections import OrderedDict

        self._cancelled_task_ids: "OrderedDict[Any, None]" = OrderedDict()
        self._current_sync_task: Optional[Tuple[Any, int]] = None
        # Task-event buffer: state transitions recorded here (any
        # thread), flushed in batches to the agent -> controller (ref:
        # task_event_buffer.h:222 periodic flush to GcsTaskManager).
        self._event_buf: List[Dict] = []
        self._event_lock = threading.Lock()
        # Streaming-generator state: per-task caller tag (notify
        # target) and ack counters for executor backpressure.
        self._stream_callers: Dict[str, str] = {}
        self._stream_acks: Dict[str, Dict[str, Any]] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # Pipelined normal-task queue (see push_task).
        from collections import deque as _deque

        self._task_queue: "_deque" = _deque()
        self._task_runner: Optional[asyncio.Task] = None
        self._task_running = False
        self._exec_blocked = False
        # Batched-exec result buffer: caller_tag -> [(reply_id, res)].
        self._result_buf: Dict[str, list] = {}
        self._flush_scheduled = False
        # Undeliverable peer notifies (owner connection mid-
        # reregistration): per-tag ordered backlog, redelivered when
        # the tag re-registers (the PROGRESS reply-loss flake: a
        # final push_actor_task reply dropped when notify_peer raced a
        # reconnect).  Loop-thread only; no lock needed.
        self._undelivered: Dict[str, "_deque"] = {}
        self._redelivery_task: Optional[asyncio.Task] = None
        # Streams declared lost by a backlog overflow: their item
        # frames are dropped and their final reply is poisoned.
        # Insertion-ordered (dict) so the size bound evicts the
        # OLDEST marks — an arbitrary eviction could drop a mark
        # whose poisoned reply is still pending, un-poisoning it.
        self._shed_streams: Dict[str, None] = {}
        for name in ["push_task", "exec_batch", "create_actor",
                     "push_actor_task", "exec_actor",
                     "cancel_task", "ping", "exit", "dump_stack",
                     "profile", "jax_profile", "stream_ack"]:
            self.server.register(name, getattr(self, name))

    async def start(self) -> None:
        self._loop = asyncio.get_event_loop()
        await self.server.start()
        self.runtime = ClusterRuntime(
            self.config,
            _connect={"session": self.session,
                      "controller": self.controller_addr,
                      "agent": self.agent_addr},
            _job_id=JobID.from_int(0))
        self.runtime.on_block = self._on_exec_block
        runtime_mod.set_runtime(self.runtime)
        await self._setup_runtime_env()
        agent = RpcClient(self.agent_addr,
                          tag=f"worker-{self.worker_id.hex()[:8]}",
                          connect_timeout=10.0)
        await agent.connect()
        phases = {"import": max(_IMPORT_DONE - _IMPORT_T0, 0.0),
                  "connect": max(time.time() - _IMPORT_DONE, 0.0)}
        if _SPAWN_TS:
            phases["spawn"] = max(_IMPORT_T0 - _SPAWN_TS, 0.0)
        await agent.call("register_worker", {
            "worker_id": self.worker_id, "addr": self.server.address,
            "pid": os.getpid(), "phases": phases})
        self._agent = agent
        # Event-loop health: scheduled-vs-actual lag ring, exported
        # with the metrics tick (rt_loop_lag_seconds -> rt doctor).
        self._loop_lag = hotpath.LoopLagSampler(self._loop)
        self._loop_lag.start()
        spawn_task(self._watch_agent())
        spawn_task(self._flush_loop())

    def _emit_event(self, spec: TaskSpec, state: str, **extra) -> None:
        ev = {"task_id": spec.task_id.hex(), "state": state,
              "ts": time.time(), "name": spec.display_name(),
              "kind": spec.kind.name, "node_id": self.node_id_hex,
              "worker_pid": os.getpid(),
              "attempt": getattr(spec, "sched_attempt", 0)}
        if spec.actor_id is not None:
            ev["actor_id"] = spec.actor_id.hex()
        ev.update(extra)
        with self._event_lock:
            self._event_buf.append(ev)
        # Mirror into the crash flight recorder so a preempted
        # worker's dump shows what it was executing: routine
        # transitions overwrite ONE sticky slot (flooding the ring at
        # batch-task rates would evict the train/collective context
        # the dump exists for); failures append as real ring events.
        from ray_tpu.util import flight_recorder

        if state == "FAILED":
            flight_recorder.record("task_failed", name=ev["name"],
                                   task_id=ev["task_id"],
                                   error=extra.get("error"))
        else:
            flight_recorder.note("last_task", name=ev["name"],
                                 state=state, task_id=ev["task_id"])

    async def _flush_loop(self) -> None:
        """Ship task events + span drains + metric snapshots on one
        cadence (the span ring rides the same agent -> controller relay
        as task events; see util/spans.py)."""
        period = max(self.config.metrics_report_period_s, 0.25)
        source = f"worker-{self.node_id_hex[:8]}-{os.getpid()}"
        last_metrics = 0.0
        while True:
            await asyncio.sleep(min(period, 1.0))
            with self._event_lock:
                batch, self._event_buf = self._event_buf, []
            try:
                if batch:
                    await self._agent.call("report_task_events",
                                           {"events": batch})
                from ray_tpu.util import spans as spans_mod

                span_batch = spans_mod.drain()
                if span_batch:
                    await self._agent.call("report_spans", {
                        "source": source,
                        "node_id": self.node_id_hex,
                        "spans": span_batch})
                # Gang watchdog: ship the set of collectives this
                # process is CURRENTLY inside (replace semantics per
                # source — an exited op vanishes on the next tick; a
                # hung one keeps refreshing, which is exactly the
                # signal the controller-side watchdog needs).  Only
                # chatty while collectives are in flight.
                from ray_tpu.collective import telemetry as _coll

                entries = _coll.inflight_entries()
                if entries or getattr(self, "_had_coll_entries",
                                      False):
                    self._had_coll_entries = bool(entries)
                    await self._agent.call(
                        "report_collective_entries", {
                            "source": source, "entries": entries})
                now = time.time()
                if now - last_metrics >= period:
                    last_metrics = now
                    import sys as _sys

                    # Device-memory watermarks ride the metrics tick,
                    # but only once user code has already paid the jax
                    # import — a no-jax worker must not drag it in.
                    if "jax" in _sys.modules:
                        try:
                            from ray_tpu.util import xprof as _xprof

                            _xprof.publish_device_memory()
                        except Exception:
                            pass
                    from ray_tpu.util.metrics import registry

                    snap = registry().snapshot()
                    # Control-plane introspection rides the same tick:
                    # loop-lag quantiles + per-method RPC handler
                    # stats, synthesized in snapshot shape.
                    lag = getattr(self, "_loop_lag", None)
                    if lag is not None:
                        snap = snap + lag.metric_snaps()
                    snap = snap + self.server.stats.metric_snaps()
                    if snap:
                        await self._agent.call("report_metrics", {
                            "source": source,
                            "snapshot": snap})
            except RpcError:
                pass  # agent gone; _watch_agent will exit us

    async def _setup_runtime_env(self) -> None:
        """Materialize working_dir / py_modules before any user code can
        run (env_vars were set by the agent at spawn).  Packages come
        from the controller KV; extraction is content-addressed and
        shared across workers on this node (ref:
        python/ray/_private/runtime_env/working_dir.py)."""
        raw = os.environ.get("RT_RUNTIME_ENV")
        if not raw:
            return
        import json

        from .. import runtime_env as renv

        spec = json.loads(raw)
        if not (spec.get("working_dir_pkg")
                or spec.get("py_modules_pkgs")):
            return
        ctl = RpcClient(self.controller_addr, connect_timeout=10.0)
        try:
            root = os.path.join(self.config.session_dir_root, self.session,
                                "runtime_envs")
            os.makedirs(root, exist_ok=True)
            # Fetch only packages not already extracted on this node —
            # the content-addressed dir is the cross-worker cache.
            blobs = {}
            for digest in ([spec.get("working_dir_pkg")] if
                           spec.get("working_dir_pkg") else []) + \
                    [e["pkg"] for e in spec.get("py_modules_pkgs", [])]:
                if os.path.isdir(os.path.join(root, digest)):
                    continue
                key = f"runtime_env/pkg/{digest}"
                blobs[key] = await ctl.call("kv_get", {"key": key})

            def kv_get(key):
                return blobs.get(key)

            cwd, paths = renv.materialize(spec, kv_get, root)
            for p in reversed(paths):
                if p not in sys.path:
                    sys.path.insert(0, p)
            if cwd:
                os.chdir(cwd)
        finally:
            await ctl.close()

    async def _watch_agent(self) -> None:
        """Exit when the node agent goes away — a worker without its node
        has no store, no lease ledger, and no reason to live."""
        while True:
            await asyncio.sleep(1.0)
            if not self._agent.connected:
                logging.warning("agent connection lost; worker exiting")
                os._exit(0)

    # ------------------------------------------------------------ execution
    def _load_func(self, spec: TaskSpec):
        fn = self._func_cache.get(spec.func_id)
        if fn is None:
            import cloudpickle  # lazy: keep prestarted forks cheap

            fn = cloudpickle.loads(spec.func_blob)
            self._func_cache[spec.func_id] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        from .object_ref import ObjectRef

        vals = []
        timeout = self.config.arg_pull_timeout_s
        for a in spec.args:
            if a.kind == ArgKind.OBJECT_REF:
                # counted=False: the owner's submitted-task hold already
                # pins the arg for this task's duration — a borrow here
                # would just be 2 extra controller RPCs per arg.  Bounded
                # timeout: a lost arg must surface ObjectLostError so the
                # owner can reconstruct and retry, not hang for hours.
                ref = ObjectRef(a.object_id, counted=False)
                vals.append(self.runtime.get([ref], timeout)[0])
            else:
                vals.append(a.value)
        nkw = len(spec.kwargs_keys)
        if nkw:
            pos, kw_vals = vals[:-nkw], vals[-nkw:]
            return pos, dict(zip(spec.kwargs_keys, kw_vals))
        return vals, {}

    def _package_one(self, spec: TaskSpec, oid, value: Any,
                     transit: list) -> Tuple[str, Any]:
        """Package one return value: ("inline", bytes) or
        ("store", (size, node_hint)); store-path objects are sealed +
        registered, embedded refs get transit/induced borrows."""
        from .object_ref import collect_embedded_refs

        with collect_embedded_refs() as embedded:
            payload, views = serialization.serialize(value)
        if embedded:
            # Any of our own in-band values whose refs ride in this
            # return must become pullable by the receiver (in-band ->
            # plane promotion; see cluster_runtime.py).
            self.runtime.promote_refs_to_plane(list(embedded))
        size = serialization.packed_size(payload, views)
        if size <= self.config.object_inline_max_bytes:
            buf = bytearray(size)
            pos = 0
            buf[pos:pos + 4] = len(views).to_bytes(4, "little"); pos += 4
            buf[pos:pos + 8] = len(payload).to_bytes(8, "little"); pos += 8
            buf[pos:pos + len(payload)] = payload; pos += len(payload)
            for v in views:
                n = len(v)
                buf[pos:pos + 8] = n.to_bytes(8, "little"); pos += 8
                buf[pos:pos + n] = v; pos += n
            if embedded:
                # Ownership handoff: hold a transit borrow on each ref
                # embedded in the payload until the owner confirms
                # receipt (released in _accept_returns) — otherwise
                # this frame's refs die and free the objects before
                # the owner ever sees them.
                holder = f"transit:{spec.task_id.hex()}"
                for emb in embedded:
                    self.runtime.controller_call(
                        "add_borrower",
                        {"object_id": emb, "holder": holder})
                transit.extend(embedded)
            return ("inline", bytes(buf))
        self.runtime.store.seal_parts(oid, payload, views)
        self.runtime.agent_call(
            "register_object", {"object_id": oid, "size": size})
        if embedded:
            # Embedded refs live as long as the container payload:
            # the controller releases these borrows when the
            # container object itself is freed.
            self.runtime.controller_call(
                "link_induced_borrows",
                {"container": oid, "embedded": list(embedded)})
        return ("store", (size, self.node_id_hex))

    def _package_returns(self, spec: TaskSpec, result: Any) -> TaskResult:
        if spec.is_streaming:
            return self._stream_returns(spec, result)
        if spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"Task {spec.display_name()} declared "
                    f"num_returns={spec.num_returns}, returned "
                    f"{len(values)}")
        entries = []
        transit: list = []
        oids = spec.return_object_ids()
        for oid, value in zip(oids, values):
            entries.append(self._package_one(spec, oid, value, transit))
        return TaskResult(task_id=spec.task_id, ok=True, returns=entries,
                          transit_refs=transit)

    # ------------------------------------------------- streaming returns
    def _stream_returns(self, spec: TaskSpec, result: Any) -> TaskResult:
        """Drive a generator task: each yielded value is packaged and
        pushed to the owner as a stream_item notify, with executor-side
        backpressure on unconsumed items (ref: _raylet.pyx:284
        ObjectRefGenerator + generator_waiter.h — the executor pauses
        when the owner lags).  Runs ON the executor thread; notify
        writes marshal to the worker's event loop."""
        import threading

        from .ids import ObjectID

        if not inspect.isgenerator(result) and \
                not hasattr(result, "__next__"):
            raise TypeError(
                f"num_returns='streaming' task "
                f"{spec.display_name()} returned "
                f"{type(result).__name__}, not a generator")
        tid = spec.task_id
        caller = self._stream_callers.get(tid.hex())
        state = self._stream_acks.setdefault(
            tid.hex(), {"consumed": 0, "event": threading.Event()})
        # 0 = unbounded (the reference default): a slow consumer must
        # never wedge the producer — and with it every task pipelined
        # behind this worker (the round-5 backpressure deadlock).
        max_pending = self.config.streaming_max_pending
        loop = self._loop
        idx = 0
        transit: list = []
        try:
            for item in result:
                idx += 1
                oid = ObjectID.for_task_return(tid, idx)
                entry = self._package_one(spec, oid, item, transit)
                payload = {"task_id": tid, "index": idx,
                           "object_id": oid, "entry": entry}
                if caller is not None:
                    loop.call_soon_threadsafe(
                        self._send_peer, caller, "stream_item",
                        payload)
                # Backpressure (bounded windows only): wait for the
                # owner to consume within max_pending of what we've
                # produced.  The wait is a BLOCKED state — it releases
                # the lease CPU and requeues tasks pipelined behind
                # this worker (without that, a stalled consumer
                # stalled every queued task forever).  A cancelled
                # task unblocks via the async-raise in cancel_task.
                if max_pending > 0 and \
                        idx - state["consumed"] > max_pending:
                    # Hysteresis: once blocked, stay blocked until the
                    # backlog drains to HALF the window.  Waking per
                    # consumed item would pay the blocked/unblocked
                    # agent round-trip (and pipeline requeue churn)
                    # for every streamed item once the consumer lags.
                    resume_gap = max(1, max_pending // 2)
                    self.runtime._notify_blocked(True)
                    try:
                        while idx - state["consumed"] > resume_gap:
                            state["event"].clear()
                            state["event"].wait(timeout=1.0)
                    finally:
                        self.runtime._notify_blocked(False)
            return TaskResult(task_id=tid, ok=True, returns=[],
                              transit_refs=transit, streamed=idx)
        except BaseException:
            # The failure TaskResult carries no transit list, so the
            # owner can't release the borrows of already-streamed
            # items — release them here or they pin objects forever.
            holder = f"transit:{tid.hex()}"
            for emb in transit:
                try:
                    self.runtime.controller_call(
                        "remove_borrower",
                        {"object_id": emb, "holder": holder})
                except Exception:
                    pass
            raise
        finally:
            self._stream_acks.pop(tid.hex(), None)
            self._stream_callers.pop(tid.hex(), None)

    def _execute_sync(self, spec: TaskSpec, fn, lease_id: Optional[int],
                      chip_ids: List[int]) -> TaskResult:
        if chip_ids:
            os.environ["TPU_VISIBLE_CHIPS"] = ",".join(map(str, chip_ids))
            os.environ.setdefault("TPU_CHIPS_PER_PROCESS_BOUNDS",
                                  f"1,{len(chip_ids)},1")
        prev_lease = self.runtime.current_lease_id
        if lease_id is not None:
            self.runtime.current_lease_id = lease_id
        prev_task = self.runtime._ctx.current_task_id
        self.runtime.set_current_task(spec.task_id)
        if spec.task_id in self._cancelled_task_ids:
            self._cancelled_task_ids.pop(spec.task_id, None)
            self.runtime.set_current_task(prev_task)
            self.runtime.current_lease_id = prev_lease
            return TaskResult(
                task_id=spec.task_id, ok=False,
                error=TaskError.from_exception(TaskCancelledError(
                    f"task {spec.display_name()} cancelled before start")))
        # Revoke any async exception still pending on this pooled thread
        # from a cancel that raced a previous task's completion — it must
        # not fire inside an unrelated task.
        import ctypes

        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(threading.get_ident()), None)
        self._current_sync_task = (spec.task_id, threading.get_ident())
        # Tracing: execute AS a child span of the submitter's context,
        # so nested .remote() calls inherit it and task events carry
        # the trace fields (ref: tracing_helper.py:88).
        span = None
        if spec.trace_ctx:
            from ..util import tracing as _tracing

            span = _tracing.child_context(spec.trace_ctx)
            _tracing.set_span_context(span)
        trace_extra = dict(span) if span else {}
        if spec.hp is not None:
            spec.hp[hotpath.EXEC_START] = time.perf_counter()
        self._emit_event(spec, "RUNNING", **trace_extra)
        try:
            pos, kwargs = self._resolve_args(spec)
            result = fn(*pos, **kwargs)
            out = self._package_returns(spec, result)
            self._emit_event(spec, "FINISHED", **trace_extra)
            return out
        except BaseException as e:  # noqa: BLE001 — shipped to owner
            kind = ActorError if spec.kind.name == "ACTOR_TASK" else TaskError
            self._emit_event(spec, "FAILED", error=repr(e),
                             **trace_extra)
            return TaskResult(task_id=spec.task_id, ok=False,
                              error=kind.from_exception(e))
        finally:
            if spec.hp is not None:
                spec.hp[hotpath.EXEC_END] = time.perf_counter()
            self._current_sync_task = None
            if spec.is_streaming:
                # A streaming task that failed before its generator
                # drive started (bad args, cancel-before-start, user
                # fn raised) must not leak its caller/ack entries.
                self._stream_callers.pop(spec.task_id.hex(), None)
                self._stream_acks.pop(spec.task_id.hex(), None)
            if span is not None:
                from ..util import tracing as _tracing

                _tracing.set_span_context(None)
            self.runtime.set_current_task(prev_task)
            self.runtime.current_lease_id = prev_lease

    # ---------------------------------------------------------- normal task
    async def push_task(self, p) -> TaskResult:
        spec: TaskSpec = p["spec"]
        env_err = os.environ.get("RT_RUNTIME_ENV_ERROR")
        if env_err:
            # This worker's runtime env failed to build (e.g. pip
            # install error); tasks fail FAST with the build error
            # instead of the agent respawning bootstraps forever (ref:
            # RuntimeEnvSetupError surfacing in runtime_env_agent).
            from .errors import RuntimeEnvSetupError

            return TaskResult(
                task_id=spec.task_id, ok=False,
                error=TaskError.from_exception(
                    RuntimeEnvSetupError(env_err)))
        if spec.is_streaming:
            self._stream_callers[spec.task_id.hex()] = \
                p.get("caller_tag", "")
        # Owners pipeline several pushes onto one leased worker (ref:
        # normal_task_submitter pipelining); an EXPLICIT queue (not
        # the executor's opaque one) lets the block hook return
        # unstarted tasks when the running task parks in get() — the
        # no-deadlock guarantee behind depth > 1.
        if self._exec_blocked and (self._task_running
                                   or self._task_queue):
            return TaskResult(task_id=spec.task_id, ok=False,
                              requeue=True)
        loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()
        self._task_queue.append((spec, p, fut))
        self._ensure_task_runner()
        return await fut

    def _ensure_task_runner(self) -> None:
        """(Re)start the drain task; a done-callback respawns it if a
        push raced the drain thread's final empty-check (that window
        spans a thread->loop handoff, so it is very real)."""
        if self._task_runner is None or self._task_runner.done():
            self._task_runner = spawn_task(self._task_runner_loop())
            self._task_runner.add_done_callback(
                lambda _t: (self._task_queue
                            and self._ensure_task_runner()))

    async def _task_runner_loop(self) -> None:
        """Drain the task queue in ONE executor submission: the thread
        body pops and executes tasks back-to-back (no per-task
        executor handoff), posting each result to the loop.  The
        block hook runs ON this same thread, so its requeue drain
        cannot race the popper."""
        loop = asyncio.get_event_loop()
        await loop.run_in_executor(self._task_executor,
                                   self._drain_queue_in_thread, loop)

    def _drain_queue_in_thread(self, loop) -> None:
        while True:
            try:
                spec, p, fut = self._task_queue.popleft()
            except IndexError:
                break
            if fut is not None and fut.done():
                continue
            self._task_running = True
            if spec.hp is not None:
                spec.hp[hotpath.WORKER_DISPATCH] = time.perf_counter()
            try:
                fn = self._load_func(spec)
                res = self._execute_sync(
                    spec, fn, p.get("lease_id"),
                    p.get("chip_ids") or [])
            except BaseException as e:  # noqa: BLE001
                res = TaskResult(task_id=spec.task_id, ok=False,
                                 error=TaskError.from_exception(e))
            finally:
                self._task_running = False
            if spec.hp is not None:
                # Echo the stamp vector on the reply so the owner can
                # close the chain (REPLY_SENT lands at flush time).
                res.hp = spec.hp
            if fut is not None:
                loop.call_soon_threadsafe(
                    lambda f=fut, r=res:
                    f.set_result(r) if not f.done() else None)
            else:
                loop.call_soon_threadsafe(
                    self._queue_result, p, res)
        loop.call_soon_threadsafe(self._flush_results)

    # ---- batched exec channel (owner notifies exec_batch; results
    # ---- return as task_results notifies; ref: the push/report split
    # ---- in core_worker.proto, batched for frame/syscall amortization)
    async def exec_batch(self, p):
        if self._exec_blocked and (self._task_running
                                   or self._task_queue):
            for item in p["tasks"]:
                self._queue_result(
                    {"caller_tag": p["caller_tag"],
                     "reply_id": item["reply_id"]},
                    TaskResult(task_id=item["spec"].task_id, ok=False,
                               requeue=True))
            self._flush_results()
            return
        env_err = os.environ.get("RT_RUNTIME_ENV_ERROR")
        for item in p["tasks"]:
            spec = item["spec"]
            ctx = {"caller_tag": p["caller_tag"],
                   "reply_id": item["reply_id"],
                   "lease_id": p.get("lease_id"),
                   "chip_ids": p.get("chip_ids") or []}
            if env_err:
                from .errors import RuntimeEnvSetupError

                self._queue_result(ctx, TaskResult(
                    task_id=spec.task_id, ok=False,
                    error=TaskError.from_exception(
                        RuntimeEnvSetupError(env_err))),
                    flush_now=True)
                continue
            if spec.is_streaming:
                self._stream_callers[spec.task_id.hex()] = \
                    p["caller_tag"]
            if spec.hp is not None:
                spec.hp[hotpath.WORKER_RECV] = time.perf_counter()
            self._task_queue.append((spec, ctx, None))
        self._ensure_task_runner()

    def _queue_result(self, ctx, res: TaskResult,
                      flush_now: bool = False) -> None:
        self._result_buf.setdefault(ctx["caller_tag"], []).append(
            (ctx["reply_id"], res))
        if flush_now or sum(len(v) for v in
                            self._result_buf.values()) >= 8:
            self._flush_results()
        elif not self._flush_scheduled:
            # Flush after the current loop burst: results completing
            # together batch into one frame, nothing waits on a timer.
            self._flush_scheduled = True
            self._loop.call_soon(self._scheduled_flush)

    def _scheduled_flush(self) -> None:
        self._flush_scheduled = False
        self._flush_results()

    def _flush_results(self) -> None:
        buf, self._result_buf = self._result_buf, {}
        for tag, entries in buf.items():
            for _rid, res in entries:
                hp = getattr(res, "hp", None)
                if hp is not None:
                    hp[hotpath.REPLY_SENT] = time.perf_counter()
            self._send_peer(tag, "task_results", {"results": entries})

    # ---- peer-notify redelivery (the reply-loss fix): a notify that
    # ---- finds the peer's tag unregistered (its connection raced a
    # ---- re-registration) is re-buffered IN ORDER and retried when
    # ---- the tag re-registers, instead of being silently dropped —
    # ---- a lost final reply left the owner waiting forever.
    # Per-tag redelivery backlog cap: a fast unbounded streaming
    # producer could otherwise grow worker RSS without limit over the
    # whole redelivery window while its owner is disconnected.  On
    # overflow the buffered STREAMS are declared lost (a partially
    # redelivered stream with a missing index would hang the consumer
    # at exhaustion — strictly worse than an error): their item
    # frames are shed and their final reply is rewritten into a
    # stream error the owner raises.  Non-stream replies are kept —
    # they are the frames the redelivery buffer exists to save.
    _UNDELIVERED_CAP = 4096

    def _apply_shed(self, method, payload) -> bool:
        """Apply the shed-stream contract to one frame: True means
        the frame is a shed stream's item and must be dropped; a shed
        stream's final reply is poisoned in place.  Every path that
        emits or redelivers a frame must route through this."""
        if not self._shed_streams:
            return False
        if method == "stream_item" and \
                payload["task_id"].hex() in self._shed_streams:
            return True
        if method == "task_results":
            self._poison_shed_results(payload)
        return False

    def _send_peer(self, tag: str, method: str, payload) -> None:
        if self._apply_shed(method, payload):
            return
        q = self._undelivered.get(tag)
        if q is not None:
            # Preserve per-peer delivery order behind the backlog.
            if len(q) >= self._UNDELIVERED_CAP:
                self._shed_overflow(tag, q)
                if self._apply_shed(method, payload):
                    return
            q.append((method, payload, time.time()))
            return
        if not self.server.notify_peer(tag, method, payload):
            from collections import deque as _dq

            self._undelivered[tag] = _dq([(method, payload,
                                           time.time())])
            self._ensure_redelivery()

    def _shed_overflow(self, tag: str, q) -> None:
        """Redelivery backlog overflow: shed every buffered stream's
        item frames (marking the streams lost) and, failing that,
        drop the oldest frame outright."""
        shed = {f[1]["task_id"].hex() for f in q
                if f[0] == "stream_item"}
        if shed:
            self._shed_streams.update(dict.fromkeys(shed))
            while len(self._shed_streams) > 1024:  # bound, oldest out
                self._shed_streams.pop(
                    next(iter(self._shed_streams)), None)
            kept = [f for f in q if f[0] != "stream_item"]
            # Final replies already buffered for a just-shed stream
            # are poisoned NOW (which also retires their marks): a
            # mark must not sit live in the bound window waiting for
            # a delivery pass that may evict it first.
            for method, payload, _ts in kept:
                if method == "task_results":
                    self._poison_shed_results(payload)
            logger.warning(
                "redelivery backlog for %s overflowed; shed %d "
                "buffered stream frame(s) — %d stream(s) to this "
                "owner will fail instead of gapping", tag,
                len(q) - len(kept), len(shed))
            q.clear()
            q.extend(kept)
        if len(q) >= self._UNDELIVERED_CAP:
            logger.warning(
                "redelivery backlog for %s still full (%d); "
                "dropping oldest undelivered frame", tag, len(q))
            q.popleft()

    def _poison_shed_results(self, payload) -> None:
        """Rewrite a shed stream's final reply into an error: its
        item frames are gone, so a successful streamed=N result
        would leave the owner waiting for items that never come."""
        for _rid, res in payload.get("results", []):
            tid = getattr(res, "task_id", None)
            if tid is not None and getattr(res, "streamed", 0) \
                    and tid.hex() in self._shed_streams:
                res.ok = False
                res.error = TaskError.from_exception(RuntimeError(
                    "stream items were dropped while the owner was "
                    "disconnected (redelivery backlog overflow)"))
                res.streamed = 0
                self._shed_streams.pop(tid.hex(), None)

    def _ensure_redelivery(self) -> None:
        if self._redelivery_task is None or \
                self._redelivery_task.done():
            self._redelivery_task = spawn_task(self._redelivery_loop())

    async def _redelivery_loop(self) -> None:
        ttl = self.config.result_redelivery_timeout_s
        while self._undelivered:
            await asyncio.sleep(0.2)
            now = time.time()
            for tag in list(self._undelivered):
                q = self._undelivered[tag]
                while q and self.server.has_peer(tag):
                    method, payload, _ts = q[0]
                    # Frames buffered before a stream was shed (TTL
                    # expiry below, or an overflow mid-backlog) must
                    # get the same treatment _send_peer applies to
                    # fresh ones: skip its items, poison its reply —
                    # redelivering them would gap the stream.
                    if self._apply_shed(method, payload):
                        q.popleft()
                        continue
                    if not self.server.notify_peer(tag, method,
                                                   payload):
                        break
                    q.popleft()
                ttl_shed = False
                while q and now - q[0][2] > ttl:
                    method, payload, ts = q.popleft()
                    if method == "stream_item":
                        # Same contract as overflow shedding: once any
                        # item frame is gone the stream can never be
                        # redelivered whole, so its surviving frames
                        # are dropped and its final reply poisoned
                        # instead of handing the owner a gapped stream
                        # with a successful result.
                        self._shed_streams[
                            payload["task_id"].hex()] = None
                        ttl_shed = True
                    logger.warning(
                        "dropping undeliverable %s for %s after "
                        "%.0fs (owner never re-registered)",
                        method, tag, now - ts)
                if ttl_shed:
                    # Retire the new marks promptly where the final
                    # reply is already buffered, as _shed_overflow
                    # does — a live mark must not wait in the bound
                    # window on a delivery pass that may never come.
                    for method, payload, _ts in q:
                        if method == "task_results":
                            self._poison_shed_results(payload)
                if not q:
                    del self._undelivered[tag]

    def _on_exec_block(self, blocked: bool) -> None:
        """Runs on the TASK THREAD when the current task blocks in
        get(): marshal a queue drain to the loop so queued-behind
        tasks fail over instead of waiting out the block."""
        self._exec_blocked = blocked
        if blocked and self._loop is not None:
            self._loop.call_soon_threadsafe(self._requeue_queued)

    def _requeue_queued(self) -> None:
        if not self._exec_blocked:
            # The blocking get resolved before this callback ran — a
            # spurious drain would bounce the whole pipeline back to
            # the owner for nothing.
            return
        while self._task_queue:
            spec, ctx, fut = self._task_queue.popleft()
            res = TaskResult(task_id=spec.task_id, ok=False,
                             requeue=True)
            if fut is not None:
                if not fut.done():
                    fut.set_result(res)
            else:
                self._queue_result(ctx, res)
        self._flush_results()

    async def stream_ack(self, p):
        """Owner consumed stream items up to ``consumed`` — release
        executor backpressure (ref: generator_waiter.h signal)."""
        st = self._stream_acks.get(p["task_id"].hex())
        if st is not None:
            st["consumed"] = max(st["consumed"], int(p["consumed"]))
            st["event"].set()
        return {"ok": True}

    # -------------------------------------------------------------- actors
    async def create_actor(self, p):
        spec: TaskSpec = p["spec"]
        env_err = os.environ.get("RT_RUNTIME_ENV_ERROR")
        if env_err:
            from .errors import RuntimeEnvSetupError

            await self._agent.call("report_actor_failure", {
                "actor_id": spec.actor_id, "creation_failed": True,
                "reason": f"runtime env setup failed: {env_err}"})
            asyncio.get_event_loop().call_later(
                0.2, self._exit_event.set)
            return {"ok": False,
                    "error": repr(RuntimeEnvSetupError(env_err))}
        chip_ids = p.get("chip_ids") or []
        if chip_ids:
            os.environ["TPU_VISIBLE_CHIPS"] = ",".join(map(str, chip_ids))
        self.runtime.current_lease_id = p.get("lease_id")
        cls = self._load_func(spec)
        loop = asyncio.get_event_loop()

        def _construct():
            self.runtime.set_current_task(spec.task_id)
            try:
                pos, kwargs = self._resolve_args(spec)
                return cls(*pos, **kwargs), None
            except BaseException as e:  # noqa: BLE001
                tb = traceback.format_exc()
                return None, (e, tb)
            finally:
                self.runtime.set_current_task(None)

        instance, err = await loop.run_in_executor(
            self._task_executor, _construct)
        if err is not None:
            exc, tb = err
            await self._agent.call("report_actor_failure", {
                "actor_id": spec.actor_id, "creation_failed": True,
                "reason": f"__init__ raised {exc!r}\n{tb}"})
            # Exit so the agent reaps this worker and frees the lease —
            # a worker that ran a failing __init__ may hold partial state.
            asyncio.get_event_loop().call_later(0.2, self._exit_event.set)
            return {"ok": False, "error": repr(exc)}
        self.actor_id = spec.actor_id
        self.runtime.current_actor_id = spec.actor_id
        self.actor_instance = instance
        n = max(1, spec.max_concurrency)
        self.actor_executor = ThreadPoolExecutor(
            max_workers=n, thread_name_prefix="actor-exec")
        self._actor_max_concurrency = n
        # Named concurrency groups (ref: concurrency_group_manager.h:34
        # + fiber.h): each group gets its OWN thread pool (sync
        # methods) and asyncio semaphore (async methods), so a slow
        # group can never starve another — the default group is the
        # base actor_executor above.  Method -> group defaults come
        # from @ray_tpu.method annotations on the class.
        self._group_executors: Dict[str, ThreadPoolExecutor] = {}
        self._group_sems: Dict[str, asyncio.Semaphore] = {}
        self._method_groups: Dict[str, str] = {}
        for gname, cap in (spec.concurrency_groups or {}).items():
            cap = max(1, int(cap))
            self._group_executors[gname] = ThreadPoolExecutor(
                max_workers=cap,
                thread_name_prefix=f"actor-{gname}")
            self._group_sems[gname] = asyncio.Semaphore(cap)
        for mname in spec.method_names:
            fn = getattr(instance, mname, None)
            mopts = getattr(fn, "__rt_method_options__", None)
            if mopts and mopts.get("concurrency_group"):
                self._method_groups[mname] = mopts["concurrency_group"]
        self._group_sems[""] = asyncio.Semaphore(n)
        # All-sync ordered actors take a queue+drain-thread fast path
        # in exec_actor (no per-call executor handoff); any coroutine
        # method forces the lock path so sync/async arrival order is
        # preserved.
        self._actor_all_sync = not any(
            inspect.iscoroutinefunction(getattr(instance, m, None))
            or inspect.isgeneratorfunction(getattr(instance, m, None))
            for m in spec.method_names)
        from collections import deque as _dq

        self._actor_call_queue: "_dq" = _dq()
        self._actor_drain: Optional[asyncio.Task] = None
        # max_concurrency=1: owners PIPELINE calls (frames arrive before
        # earlier replies are sent), so ordering must be enforced here —
        # one FIFO lock serializing sync and async methods in arrival
        # order (asyncio.Lock wakes waiters FIFO; handler tasks start in
        # frame-arrival order).  Ref: ActorSchedulingQueue in
        # transport/task_receiver.h executing in sequence-number order.
        self._actor_exec_lock = (asyncio.Lock()
                                 if n == 1
                                 and not self._group_executors
                                 else None)
        from .ids import NodeID

        # Through the agent's batched relay (one persistent controller
        # connection, bulk actors_started frames on a 5 ms window) —
        # NOT a fresh per-actor controller dial: a 100-replica fan-out
        # registers in a handful of round trips.
        r = await self._agent.call("report_actor_started", {
            "actor_id": spec.actor_id,
            "node_id": NodeID.from_hex(self.node_id_hex),
            "worker_addr": self.server.address})
        if r.get("kill"):
            self._exit_event.set()
            return {"ok": False, "error": "actor killed during creation"}
        return {"ok": True}

    async def push_actor_task(self, p) -> TaskResult:
        spec: TaskSpec = p["spec"]
        caller = p.get("caller_id", "?")
        if self.actor_instance is None:
            return TaskResult(
                task_id=spec.task_id, ok=False,
                error=ActorError.from_exception(
                    RuntimeError("actor not initialized on this worker")))
        method = getattr(self.actor_instance, spec.method_name, None)
        if method is None:
            return TaskResult(
                task_id=spec.task_id, ok=False,
                error=ActorError.from_exception(AttributeError(
                    f"actor has no method {spec.method_name!r}")))
        del caller
        if spec.is_streaming:
            self._stream_callers[spec.task_id.hex()] = \
                p.get("caller_tag", "")
        lock = getattr(self, "_actor_exec_lock", None)
        if lock is not None and getattr(self, "_actor_all_sync", False):
            # All-sync ordered actor: route through the SAME queue as
            # exec_batch arrivals.  Taking the lock directly here could
            # win it before an earlier exec_actor's drain task starts,
            # executing this later call first — mixed submission paths
            # must not violate arrival-order execution.
            loop = asyncio.get_event_loop()
            fut: asyncio.Future = loop.create_future()
            self._actor_call_queue.append((spec, method, fut))
            self._ensure_actor_drain()
            return await fut
        if lock is not None:
            async with lock:
                return await self._run_actor_method(spec, method)
        return await self._run_actor_method(spec, method)

    def _resolve_group(self, spec: TaskSpec) -> str:
        """Per-call override beats the method's declared group; ""
        (unknown groups fall back to the default pool with a warning
        rather than failing the call)."""
        group = spec.concurrency_group or \
            self._method_groups.get(spec.method_name, "")
        if group and group not in self._group_executors:
            logger.warning("unknown concurrency group %r for %s; "
                           "using default", group, spec.method_name)
            return ""
        return group

    async def _run_actor_method(self, spec: TaskSpec, method
                                ) -> TaskResult:
        group = self._resolve_group(spec)
        if inspect.iscoroutinefunction(method):
            sem = self._group_sems.get(group)
            if sem is not None:
                async with sem:
                    return await self._run_async_method(spec, method)
            return await self._run_async_method(spec, method)
        executor = self._group_executors.get(group,
                                             self.actor_executor)
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(
            executor, self._execute_sync, spec, method, None, [])

    async def _run_async_method(self, spec: TaskSpec, method) -> TaskResult:
        # NOTE: no set_current_task here — the task context is a
        # thread-local shared by every coroutine on this loop, and
        # concurrent async methods would cross-contaminate it (object
        # IDs stay unique regardless: the put counter is process-global).
        loop = asyncio.get_event_loop()
        # Tracing parity with _execute_sync: async methods execute AS a
        # child span of the submitter's context.  Safe to set here: the
        # span context is a contextvars.ContextVar and each RPC dispatch
        # runs in its own asyncio task with its own context copy, so
        # concurrent coroutines cannot cross-contaminate — and nested
        # .remote() calls made from this method now inherit the span
        # (previously a documented limitation of the thread-local).
        trace_extra = {}
        span = None
        if spec.trace_ctx:
            from ..util import tracing as _tracing

            span = _tracing.child_context(spec.trace_ctx)
            _tracing.set_span_context(span)
            trace_extra = dict(span or {})
        self._emit_event(spec, "RUNNING", **trace_extra)
        try:
            # Arg resolution may block on remote objects; keep it off the
            # event loop so other handlers stay live.
            pos, kwargs = await loop.run_in_executor(
                self._task_executor, self._resolve_args, spec)
            result = await method(*pos, **kwargs)
            out = await loop.run_in_executor(
                self._task_executor, self._package_returns, spec, result)
            self._emit_event(spec, "FINISHED", **trace_extra)
            return out
        except BaseException as e:  # noqa: BLE001
            self._emit_event(spec, "FAILED", error=repr(e),
                             **trace_extra)
            return TaskResult(task_id=spec.task_id, ok=False,
                              error=ActorError.from_exception(e))

    async def exec_actor(self, p):
        """Notify-based actor call: like push_actor_task but the
        result returns through the batched task_results channel (one
        response frame per burst instead of per call)."""
        spec: TaskSpec = p["spec"]
        ctx = {"caller_tag": p["caller_tag"],
               "reply_id": p["reply_id"]}
        if self.actor_instance is None:
            self._queue_result(ctx, TaskResult(
                task_id=spec.task_id, ok=False,
                error=ActorError.from_exception(RuntimeError(
                    "actor not initialized on this worker"))))
            return
        method = getattr(self.actor_instance, spec.method_name, None)
        if method is None:
            self._queue_result(ctx, TaskResult(
                task_id=spec.task_id, ok=False,
                error=ActorError.from_exception(AttributeError(
                    f"actor has no method {spec.method_name!r}"))))
            return
        if spec.is_streaming:
            self._stream_callers[spec.task_id.hex()] = \
                p.get("caller_tag", "")
        lock = getattr(self, "_actor_exec_lock", None)
        if lock is not None and self._actor_all_sync:
            # No generator/coroutine methods exist on this actor (the
            # _actor_all_sync predicate excludes them), so every call
            # takes THIS path — the lock path below can never
            # interleave out of arrival order with the queue.
            # Ordered all-sync actor: drain calls back-to-back on the
            # actor thread (arrival order == queue order == execution
            # order; one executor submission per burst).
            self._actor_call_queue.append((spec, method, ctx))
            self._ensure_actor_drain()
            return
        if lock is not None:
            async with lock:
                res = await self._run_actor_method(spec, method)
        else:
            res = await self._run_actor_method(spec, method)
        self._queue_result(ctx, res)

    def _ensure_actor_drain(self) -> None:
        if self._actor_drain is None or self._actor_drain.done():
            self._actor_drain = spawn_task(self._actor_drain_loop())
            self._actor_drain.add_done_callback(
                lambda _t: (self._actor_call_queue
                            and self._ensure_actor_drain()))

    async def _actor_drain_loop(self) -> None:
        loop = asyncio.get_event_loop()
        lock = self._actor_exec_lock
        async with lock:   # serialize vs push_actor_task arrivals
            await loop.run_in_executor(
                self.actor_executor, self._drain_actor_calls, loop)

    def _drain_actor_calls(self, loop) -> None:
        while True:
            try:
                spec, method, ctx = self._actor_call_queue.popleft()
            except IndexError:
                break
            res = self._execute_sync(spec, method, None, [])
            if isinstance(ctx, dict):  # exec_actor notify path
                loop.call_soon_threadsafe(self._queue_result, ctx, res)
            else:  # push_actor_task future
                loop.call_soon_threadsafe(
                    lambda f=ctx, r=res:
                    f.set_result(r) if not f.done() else None)
        loop.call_soon_threadsafe(self._flush_results)

    async def cancel_task(self, p):
        """Best-effort in-band cancellation (ref: core_worker CancelTask →
        KeyboardInterrupt in the executing thread).  A running task gets
        TaskCancelledError raised asynchronously in its thread; a queued
        task is marked so it errors out instead of starting."""
        tid = p["task_id"]
        cur = self._current_sync_task
        if cur is not None and cur[0] == tid:
            import ctypes

            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(cur[1]),
                ctypes.py_object(TaskCancelledError))
            if self._current_sync_task != cur:
                # The task finished before delivery; revoke so the
                # pending exception can't fire in the next task (the
                # next _execute_sync also clears at entry as a backstop).
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(cur[1]), None)
            return {"ok": True, "interrupted": True}
        self._cancelled_task_ids[tid] = None
        while len(self._cancelled_task_ids) > 512:
            self._cancelled_task_ids.popitem(last=False)
        return {"ok": True, "interrupted": False}

    # --------------------------------------------------------------- admin
    async def ping(self, _p):
        return {"ok": True, "actor": self.actor_id.hex()
                if self.actor_id else None}

    async def exit(self, _p):
        self._exit_event.set()
        return {"ok": True}

    async def dump_stack(self, _p):
        """All-thread stack dump (ref: profile_manager.py py-spy
        --dump, redesigned in-process — see util/profiling.py)."""
        from ..util.profiling import dump_stacks

        return {"ok": True, "stacks": dump_stacks()}

    async def profile(self, p):
        """Sampling profile of this worker's threads; returns folded
        stacks.  Runs in a thread so the RPC loop stays responsive."""
        from ..util.profiling import sample_profile

        duration = min(float(p.get("duration_s", 2.0)), 60.0)
        hz = min(float(p.get("hz", 100.0)), 500.0)
        folded = await asyncio.get_event_loop().run_in_executor(
            None, lambda: sample_profile(duration, hz))
        return {"ok": True, "folded": folded}

    async def jax_profile(self, p):
        """On-demand jax.profiler capture (`rt profile --jax`): trace
        whatever this worker's jax runtime does for ``duration_s`` into
        a TensorBoard-loadable directory and return its path.  Guarded:
        jax is only touched if user code ALREADY imported it in this
        process (tier-1 CPU runs and non-ML workers must never pay the
        jax import); ``force`` opts into importing it anyway."""
        if "jax" not in sys.modules and not p.get("force"):
            return {"ok": False,
                    "error": "jax not imported in this worker "
                             "(pass force=True to load it)"}
        duration = min(float(p.get("duration_s", 3.0)), 120.0)
        log_dir = p.get("log_dir") or os.path.join(
            self.config.session_dir_root, self.session, "profiles",
            f"jax-{self.node_id_hex[:8]}-{os.getpid()}-"
            f"{int(time.time())}")

        def _capture():
            import jax

            os.makedirs(log_dir, exist_ok=True)
            jax.profiler.start_trace(log_dir)
            try:
                # The capture window: jax activity on OTHER threads
                # (the train loop) lands in the trace while we sleep.
                time.sleep(duration)
            finally:
                jax.profiler.stop_trace()
            return log_dir

        try:
            path = await asyncio.get_event_loop().run_in_executor(
                None, _capture)
        except BaseException as e:  # noqa: BLE001 — shipped to caller
            return {"ok": False, "error": repr(e)}
        return {"ok": True, "path": path}

    async def run_forever(self):
        await self._exit_event.wait()


def main() -> None:
    logging.basicConfig(
        level=getattr(logging,
                      os.environ.get("RT_LOG_LEVEL", "INFO").upper(),
                      logging.INFO),
        format=f"%(asctime)s worker[{os.getpid()}] %(levelname)s %(message)s")
    # Debug hook: `kill -USR1 <worker pid>` dumps every thread's stack
    # to the worker log (the reference exposes py-spy via the dashboard;
    # this is the dependency-free equivalent for hung-worker triage).
    faulthandler.register(_signal.SIGUSR1, all_threads=True)
    # Crash flight recorder: dump the telemetry ring on SIGTERM or an
    # uncaught exception so postmortems on preempted slices are
    # possible.  Must install from the main thread (signal handler).
    try:
        from ray_tpu.util import flight_recorder

        cfg = RuntimeConfig.from_env()
        flight_recorder.install(
            dump_dir=os.path.join(cfg.session_dir_root,
                                  os.environ["RT_SESSION_NAME"],
                                  "flight"),
            source=f"worker-{os.environ['RT_NODE_ID'][:8]}"
                   f"-{os.getpid()}")
    except Exception:
        logging.debug("flight recorder install failed", exc_info=True)

    async def _run():
        w = Worker()
        await w.start()
        await w.run_forever()

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
