"""Public task/actor API: ``remote``, ``get``, ``put``, ``wait``, actors.

Role-equivalent to the reference's frontend (ref:
python/ray/remote_function.py:303 RemoteFunction._remote,
python/ray/actor.py ActorClass/ActorHandle, python/ray/_private/worker.py
get/put/wait).  All calls delegate to the active Runtime backend (local or
cluster); specs are built here so both backends share one code path.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union


from . import runtime as _runtime_mod
from .ids import ActorID
from .object_ref import ObjectRef
from .resources import task_resources
from .task import (ArgKind, SchedulingStrategy, TaskArg, TaskKind, TaskSpec,
                   func_id_of)

_DEFAULT_OPTIONS = dict(
    num_cpus=None,
    num_tpus=None,
    memory=None,
    resources=None,
    num_returns=1,
    max_retries=None,
    retry_exceptions=False,
    name="",
    max_restarts=0,
    max_task_retries=0,
    # None = unset: sync actors resolve to 1, async actors to 1000
    # (the reference's DEFAULT_MAX_CONCURRENCY_ASYNC).  An EXPLICIT
    # max_concurrency=1 on an async actor is honored, not bumped.
    max_concurrency=None,
    concurrency_groups=None,
    lifetime=None,
    namespace="",
    scheduling_strategy=None,
    runtime_env=None,
    get_if_exists=False,
)


def _merge_options(base: Dict[str, Any], **updates) -> Dict[str, Any]:
    out = dict(base)
    for k, v in updates.items():
        if k not in _DEFAULT_OPTIONS:
            raise TypeError(f"Unknown option {k!r}")
        if k == "runtime_env" and v:
            # Validate eagerly so a bad env raises here, in the caller's
            # thread, not inside the async submit path.
            from .. import runtime_env as _renv

            _renv.normalize(v)
        out[k] = v
    return out


def _build_args(args: Tuple, kwargs: Dict[str, Any]) -> Tuple[List[TaskArg], List[str]]:
    task_args: List[TaskArg] = []
    for a in args:
        if isinstance(a, ObjectRef):
            task_args.append(TaskArg(ArgKind.OBJECT_REF, object_id=a.id))
        else:
            task_args.append(TaskArg(ArgKind.VALUE, value=a))
    kw_keys = []
    for k, v in kwargs.items():
        kw_keys.append(k)
        if isinstance(v, ObjectRef):
            task_args.append(TaskArg(ArgKind.OBJECT_REF, object_id=v.id))
        else:
            task_args.append(TaskArg(ArgKind.VALUE, value=v))
    return task_args, kw_keys


def _strategy(opts: Dict[str, Any]) -> SchedulingStrategy:
    s = opts.get("scheduling_strategy")
    if s is None:
        return SchedulingStrategy()
    if isinstance(s, SchedulingStrategy):
        return s
    if s == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if s == "DEFAULT":
        return SchedulingStrategy()
    # User-facing strategy objects (ref: util/scheduling_strategies.py).
    kind = type(s).__name__
    if kind == "PlacementGroupSchedulingStrategy":
        if getattr(s, "placement_group_capture_child_tasks", False):
            raise NotImplementedError(
                "placement_group_capture_child_tasks is not supported yet; "
                "bind child tasks explicitly with their own "
                "PlacementGroupSchedulingStrategy")
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            placement_group_id=s.placement_group.id,
            bundle_index=s.placement_group_bundle_index)
    if kind == "NodeAffinitySchedulingStrategy":
        return SchedulingStrategy(kind="NODE_AFFINITY",
                                  node_id=s.to_node_id(), soft=s.soft)
    if kind == "NodeLabelSchedulingStrategy":
        # Resolve hard labels to a concrete node now (labels are static
        # per node: TPU slice/pod identity).
        from . import runtime as _rt

        nodes = _rt.get_runtime().nodes()
        hard = s.hard or {}
        for n in nodes:
            if n["Alive"] and all(n["Labels"].get(k) == v
                                  for k, v in hard.items()):
                from .ids import NodeID

                return SchedulingStrategy(
                    kind="NODE_AFFINITY",
                    node_id=NodeID.from_hex(n["NodeID"]), soft=False)
        raise ValueError(f"no alive node matches labels {hard!r}")
    raise ValueError(f"Unknown scheduling strategy {s!r}")


def method(*, concurrency_group: str = "",
           num_returns: Optional[Any] = None):
    """``@ray_tpu.method(concurrency_group=...)`` — per-method actor
    options (ref: ray.method + concurrency_group_manager.h:34: methods
    bind to a named concurrency group; calls may override per-call via
    ``.options(concurrency_group=...)``)."""

    def wrap(fn):
        fn.__rt_method_options__ = {
            "concurrency_group": concurrency_group,
            "num_returns": num_returns,
        }
        return fn

    return wrap


class RemoteFunction:
    """A function decorated with ``@remote``; call via ``.remote(...)``."""

    def __init__(self, func, options: Dict[str, Any]):
        self._func = func
        self._options = options
        self._blob: Optional[bytes] = None
        self._func_id: Optional[str] = None
        functools.update_wrapper(self, func)

    def _ensure_blob(self) -> Tuple[str, bytes]:
        if self._blob is None:
            from . import serialization as _ser

            self._blob = _ser.dumps_code(self._func)
            self._func_id = func_id_of(self._blob)
        return self._func_id, self._blob

    def options(self, **updates) -> "RemoteFunction":
        rf = RemoteFunction(self._func, _merge_options(self._options, **updates))
        rf._blob, rf._func_id = self._blob, self._func_id
        return rf

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        rt = _runtime_mod.get_runtime()
        func_id, blob = self._ensure_blob()
        opts = self._options
        task_args, kw_keys = _build_args(args, kwargs)
        cfg = rt.config
        max_retries = opts["max_retries"]
        if max_retries is None:
            max_retries = cfg.max_task_retries
        spec = TaskSpec(
            task_id=rt.next_task_id(),
            job_id=rt.job_id,
            kind=TaskKind.NORMAL,
            func_id=func_id,
            func_blob=blob,
            args=task_args,
            kwargs_keys=kw_keys,
            num_returns=(TaskSpec.STREAMING
                         if opts["num_returns"] in ("streaming",
                                                    "dynamic")
                         else opts["num_returns"]),
            resources=task_resources(
                opts["num_cpus"], opts["num_tpus"], opts["memory"],
                opts["resources"]),
            max_retries=max_retries,
            retry_exceptions=opts["retry_exceptions"],
            name=opts["name"] or getattr(self._func, "__name__", ""),
            scheduling=_strategy(opts),
            runtime_env=opts["runtime_env"],
        )
        from ..util import hotpath, tracing

        # Injected when tracing is on OR a serve request context is
        # active (request-scoped tracing works without the flag).
        tracing.maybe_inject(spec, cfg.tracing_enabled)
        # Hot-path introspection: a sampled 1-in-N task carries a
        # phase-stamp vector through the whole lifecycle (rt hotpath).
        hotpath.maybe_sample(spec, cfg.hotpath_sample)
        refs = rt.submit_task(spec)
        if spec.is_streaming:
            return refs[0]  # an ObjectRefGenerator
        return refs[0] if spec.num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{getattr(self._func, '__name__', '?')}' cannot "
            f"be called directly; use .remote()."
        )


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1, concurrency_group: str = ""):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, **updates) -> "ActorMethod":
        m = ActorMethod(self._handle, self._name, self._num_returns,
                        self._concurrency_group)
        if "num_returns" in updates:
            m._num_returns = updates.pop("num_returns")
        if "concurrency_group" in updates:
            m._concurrency_group = updates.pop("concurrency_group")
        if updates:
            raise TypeError(f"Unsupported actor-method options: {list(updates)}")
        return m

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._name, args, kwargs, self._num_returns,
            concurrency_group=self._concurrency_group)

    def bind(self, *args):
        """Build a DAG node from this method (ref: dag_node bind)."""
        from ..dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args)


class ActorHandle:
    """Client-side handle to a live actor; picklable into tasks."""

    def __init__(self, actor_id: ActorID, class_name: str,
                 method_names: List[str], namespace: str = "",
                 max_concurrency: int = 1, has_groups: bool = False,
                 method_options: Optional[Dict[str, Dict]] = None,
                 group_names: Optional[List[str]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_names = list(method_names)
        self._namespace = namespace
        self._max_concurrency = max_concurrency
        self._has_groups = has_groups
        self._method_options = dict(method_options or {})
        self._group_names = list(group_names or [])

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._method_names:
            raise AttributeError(
                f"Actor {self._class_name} has no method {name!r}")
        mopts = self._method_options.get(name, {})
        return ActorMethod(
            self, name,
            num_returns=mopts.get("num_returns") or 1,
            concurrency_group=mopts.get("concurrency_group") or "")

    def _submit_method(self, method: str, args, kwargs, num_returns,
                       concurrency_group: str = ""):
        rt = _runtime_mod.get_runtime()
        if num_returns in ("streaming", "dynamic"):
            num_returns = TaskSpec.STREAMING
        task_args, kw_keys = _build_args(args, kwargs)
        if concurrency_group and self._group_names and \
                concurrency_group not in self._group_names:
            raise ValueError(
                f"unknown concurrency group {concurrency_group!r}; "
                f"declared: {self._group_names}")
        spec = TaskSpec(
            task_id=rt.next_actor_task_id(self._actor_id),
            job_id=rt.job_id,
            kind=TaskKind.ACTOR_TASK,
            func_id="",
            method_name=method,
            args=task_args,
            kwargs_keys=kw_keys,
            num_returns=num_returns,
            actor_id=self._actor_id,
            seq_no=rt.next_actor_seq(self._actor_id),
            max_concurrency=self._max_concurrency,
            concurrency_group=concurrency_group,
            # Grouped actors execute per-group: submission must not
            # serialize (ref: per-group scheduling queues).
            unordered=self._has_groups,
            name=f"{self._class_name}.{method}",
        )
        from ..util import tracing

        tracing.maybe_inject(spec, rt.config.tracing_enabled)
        refs = rt.submit_actor_task(spec)
        if spec.is_streaming:
            return refs[0]  # an ObjectRefGenerator
        return refs[0] if num_returns == 1 else refs

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_names, self._namespace,
                              self._max_concurrency,
                              self._has_groups, self._method_options,
                              self._group_names))


class ActorClass:
    """A class decorated with ``@remote``; instantiate via ``.remote(...)``."""

    def __init__(self, cls, options: Dict[str, Any]):
        # Inject the compiled-DAG resident loop as an actor method (ref:
        # compiled DAGs' do_exec_tasks entrypoint on every actor).  The
        # rt_-prefixed name is reserved; always set it so a user
        # attribute of the same name cannot silently receive
        # loop-protocol arguments.
        from ..dag import _dag_exec_loop

        try:
            cls.rt_dag_exec_loop = _dag_exec_loop
        except (AttributeError, TypeError):
            pass  # frozen/extension classes opt out of DAG support
        self._cls = cls
        self._options = options
        self._blob: Optional[bytes] = None
        self._func_id: Optional[str] = None

    def options(self, **updates) -> "ActorClass":
        ac = ActorClass(self._cls, _merge_options(self._options, **updates))
        ac._blob, ac._func_id = self._blob, self._func_id
        return ac

    def _ensure_blob(self):
        if self._blob is None:
            from . import serialization as _ser

            self._blob = _ser.dumps_code(self._cls)
            self._func_id = func_id_of(self._blob)
        return self._func_id, self._blob

    def _method_names(self) -> List[str]:
        return [
            n for n, _ in inspect.getmembers(self._cls, callable)
            if not n.startswith("__")
        ]

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = _runtime_mod.get_runtime()
        opts = self._options
        name = opts["name"]
        if name and opts["get_if_exists"]:
            try:
                return rt.get_named_actor(name, opts["namespace"])
            except ValueError:
                pass
        func_id, blob = self._ensure_blob()
        actor_id = rt.next_actor_id()
        method_names = self._method_names()
        task_args, kw_keys = _build_args(args, kwargs)
        res = task_resources(
            opts["num_cpus"], opts["num_tpus"], opts["memory"],
            opts["resources"], default_cpus=1.0)
        max_concurrency = opts["max_concurrency"]
        groups = dict(opts["concurrency_groups"] or {})
        method_options: Dict[str, Dict[str, Any]] = {}
        for n in method_names:
            mo = getattr(getattr(self._cls, n, None),
                         "__rt_method_options__", None)
            if mo:
                method_options[n] = dict(mo)
                g = mo.get("concurrency_group")
                if g and g not in groups:
                    raise ValueError(
                        f"method {n!r} declares concurrency group "
                        f"{g!r} but the actor only defines "
                        f"{sorted(groups)} — typo'd group names must "
                        f"fail at creation, not fall back silently")
        has_async = any(
            inspect.iscoroutinefunction(getattr(self._cls, n, None))
            for n in method_names)
        if max_concurrency is None:
            # Unset: async actors interleave natively; default their
            # window like the reference (ref:
            # DEFAULT_MAX_CONCURRENCY_ASYNC = 1000 for asyncio actors)
            # — including grouped actors, whose DEFAULT group would
            # otherwise serialize await-holding methods into a
            # deadlock.  An explicit max_concurrency=1 is honored:
            # code relying on serialized async actors must not get
            # surprise interleaving.  (Corollary: an EXPLICIT 1 on an
            # async actor whose default-group methods await each other
            # can deadlock — that's now the caller's stated choice,
            # same as the reference.)
            max_concurrency = 1000 if has_async else 1
        elif max_concurrency < 1:
            raise ValueError(
                f"max_concurrency must be >= 1, got {max_concurrency}")
        spec = TaskSpec(
            task_id=rt.actor_creation_task_id(actor_id),
            job_id=rt.job_id,
            kind=TaskKind.ACTOR_CREATION,
            func_id=func_id,
            func_blob=blob,
            args=task_args,
            kwargs_keys=kw_keys,
            num_returns=1,
            resources=res,
            max_restarts=opts["max_restarts"],
            max_concurrency=max_concurrency,
            concurrency_groups=groups,
            method_options=method_options,
            actor_id=actor_id,
            actor_name=name,
            namespace=opts["namespace"],
            method_names=method_names,
            lifetime=opts["lifetime"],
            name=f"{self._cls.__name__}.__init__",
            scheduling=_strategy(opts),
            runtime_env=opts["runtime_env"],
        )
        try:
            rt.create_actor(spec)
        except ValueError:
            if name and opts["get_if_exists"]:
                # Lost a creation race; return the winner's handle.
                return rt.get_named_actor(name, opts["namespace"])
            raise
        return ActorHandle(actor_id, self._cls.__name__, method_names,
                           opts["namespace"], max_concurrency,
                           has_groups=bool(groups),
                           method_options=method_options,
                           group_names=sorted(groups))

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated "
            f"directly; use .remote()."
        )


def remote(*args, **options):
    """``@remote`` decorator for functions and classes.

    Usage: ``@remote`` or ``@remote(num_cpus=2, num_tpus=1, ...)``.
    """
    if len(args) == 1 and not options and (inspect.isfunction(args[0])
                                           or inspect.isclass(args[0])):
        target = args[0]
        opts = dict(_DEFAULT_OPTIONS)
        if inspect.isclass(target):
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)
    if args:
        raise TypeError("remote() takes keyword options only")
    opts = _merge_options(_DEFAULT_OPTIONS, **options)

    def wrap(target):
        if inspect.isclass(target):
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)

    return wrap


# ---------------------------------------------------------------------------
# Module-level object API.
# ---------------------------------------------------------------------------

def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return _runtime_mod.get_runtime().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    rt = _runtime_mod.get_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout)[0]
    if isinstance(refs, (list, tuple)):
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(f"get() expects ObjectRefs, got {type(bad[0])}")
        return rt.get(list(refs), timeout)
    raise TypeError(f"get() expects an ObjectRef or a list, got {type(refs)}")


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None,
         fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if not refs:
        return [], []
    if num_returns <= 0 or num_returns > len(refs):
        raise ValueError(f"num_returns must be in [1, {len(refs)}]")
    return _runtime_mod.get_runtime().wait(list(refs), num_returns, timeout,
                                           fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _runtime_mod.get_runtime().kill_actor(actor.actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    _runtime_mod.get_runtime().cancel(ref, force)


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    return _runtime_mod.get_runtime().get_named_actor(name, namespace)


class RuntimeContext:
    """Introspection handle for the current process/task (ref:
    python/ray/runtime_context.py RuntimeContext — get_job_id,
    get_task_id, get_actor_id, get_node_id)."""

    def __init__(self, rt):
        self._rt = rt

    def get_job_id(self) -> str:
        return self._rt.job_id.hex()

    def get_task_id(self):
        tid = self._rt.current_task_id()
        return tid.hex() if tid is not None else None

    def get_actor_id(self):
        aid = getattr(self._rt, "current_actor_id", None)
        return aid.hex() if aid is not None else None

    def get_node_id(self):
        import os

        return os.environ.get("RT_NODE_ID")


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_runtime_mod.get_runtime())
