"""Spawning of controller / node-agent processes.

Role-equivalent to the reference's service launcher (ref:
python/ray/_private/services.py start_gcs_server:1445 /
start_raylet:1523): builds command lines, wires ready-pipes, and captures
logs under the session directory.  Shared by the driver head bring-up and
the multi-node test Cluster fixture (ref: python/ray/cluster_utils.py:135).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional, Tuple

from .config import RuntimeConfig


def _spawn(args, env, log_path: str, pass_fd: int) -> subprocess.Popen:
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    out = open(log_path, "ab")
    try:
        return subprocess.Popen(
            args, env=env, stdout=out, stderr=subprocess.STDOUT,
            pass_fds=(pass_fd,), start_new_session=True)
    finally:
        out.close()


def _read_ready(r_fd: int, proc: subprocess.Popen, what: str,
                timeout: float = 60.0) -> str:
    buf = b""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            os.close(r_fd)
            raise RuntimeError(
                f"{what} exited during startup (code {proc.returncode})")
        chunk = os.read(r_fd, 256)
        if chunk:
            buf += chunk
            if b"\n" in buf:
                break
        else:
            break
    os.close(r_fd)
    if b"\n" not in buf:
        raise RuntimeError(f"{what} did not report ready")
    return buf.decode().strip()


def _base_env(config: RuntimeConfig) -> Dict[str, str]:
    env = dict(os.environ)
    env.update(config.env_overrides())
    # Children must find ray_tpu even when the driver got it via a
    # sys.path edit rather than an installed package.
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    existing = env.get("PYTHONPATH", "")
    if pkg_parent not in existing.split(os.pathsep):
        env["PYTHONPATH"] = (pkg_parent + os.pathsep + existing
                             if existing else pkg_parent)
    return env


def log_dir_of(config: RuntimeConfig, session: str) -> str:
    return os.path.join(config.session_dir_root, session, "logs")


def start_controller(config: RuntimeConfig, session: str,
                     driver_pid: int = 0, port: int = 0
                     ) -> Tuple[subprocess.Popen, str]:
    r_fd, w_fd = os.pipe()
    args = [sys.executable, "-u", "-m", "ray_tpu.core.controller",
            "--session", session, "--ready-fd", str(w_fd)]
    if port:
        args += ["--port", str(port)]
    if driver_pid:
        args += ["--driver-pid", str(driver_pid)]
    proc = _spawn(
        args, _base_env(config),
        os.path.join(log_dir_of(config, session), "controller.log"), w_fd)
    os.close(w_fd)
    line = _read_ready(r_fd, proc, "controller")
    return proc, line.split()[0]


def start_node_agent(
    config: RuntimeConfig, session: str, controller_addr: str, *,
    num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
    custom_resources: Optional[Dict[str, float]] = None,
    is_head: bool = False, tag: str = "node",
) -> Tuple[subprocess.Popen, str, str]:
    """Returns (process, agent_addr, node_id_hex)."""
    r_fd, w_fd = os.pipe()
    args = [sys.executable, "-u", "-m", "ray_tpu.core.node_agent",
            "--session", session, "--controller", controller_addr,
            "--ready-fd", str(w_fd)]
    if is_head:
        args.append("--head")
    if num_cpus is not None:
        args += ["--num-cpus", str(num_cpus)]
    if num_tpus is not None:
        args += ["--num-tpus", str(num_tpus)]
    if custom_resources:
        args += ["--resources", json.dumps(custom_resources)]
    proc = _spawn(
        args, _base_env(config),
        os.path.join(log_dir_of(config, session), f"agent-{tag}.log"), w_fd)
    os.close(w_fd)
    line = _read_ready(r_fd, proc, "node agent")
    parts = line.split()
    return proc, parts[0], parts[1]
