"""Lightweight asyncio RPC used by every control-plane process.

Role-equivalent to the reference's gRPC layer (ref: src/ray/rpc/ —
GrpcServer, ClientCallManager) rebuilt on asyncio streams with
length-prefixed pickled frames.  Design notes for the TPU build: the
control plane only moves small host metadata (tensors move in-graph over
ICI or through the shared-memory object plane), so a single-connection
multiplexed byte protocol is sufficient and keeps the runtime free of
codegen; retries/reconnects live in ``RpcClient`` the way the reference
puts them in ``retryable_grpc_client``.

Frame layout: ``u32 length | pickled (kind, req_id, method, payload)`` where
kind is REQUEST/RESPONSE/ERROR/NOTIFY.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import threading
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

# cloudpickle loads on the first frame encode, not at import:
# rpc sits on every process's spawn path (see core/serialization
# for the same discipline).
_cloudpickle = None


def _cp():
    global _cloudpickle
    if _cloudpickle is None:
        import cloudpickle

        _cloudpickle = cloudpickle
    return _cloudpickle

logger = logging.getLogger(__name__)

_REQUEST = 0
_RESPONSE = 1
_ERROR = 2
_NOTIFY = 3

_MAX_FRAME = 1 << 34  # 16 GiB safety cap for object transfer frames


class RpcError(ConnectionError):
    """Transport-level failure (peer died / connection refused)."""


class RemoteCallError(Exception):
    """The handler on the peer raised; carries the original exception."""

    def __init__(self, cause: BaseException):
        self.cause = cause
        super().__init__(repr(cause))


async def _read_frame(reader: asyncio.StreamReader) -> Tuple:
    header = await reader.readexactly(8)
    n = int.from_bytes(header, "little")
    if n > _MAX_FRAME:
        raise RpcError(f"frame too large: {n}")
    data = await reader.readexactly(n)
    return pickle.loads(data)


def _encode_frame(msg: Tuple) -> bytes:
    # 8-byte length prefix: object-transfer frames can exceed 4 GiB.
    data = _cp().dumps(msg, protocol=5)
    return len(data).to_bytes(8, "little") + data


def _encode_frame_fast(msg: Tuple) -> bytes:
    """Server->client frames (responses, result/stream notifies): try
    the C pickler first — ~3x cheaper than cloudpickle on the hot
    control frames.  Safety: plain pickle serializes importable
    objects BY REFERENCE exactly like cloudpickle does, and anything
    pickle rejects (closures, __main__ definitions not importable
    here) falls back to cloudpickle — so this path introduces no new
    cross-process failure modes; client->server REQUESTS keep
    cloudpickle because driver-__main__ objects pickle by name there
    and would dangle on the worker."""
    try:
        data = pickle.dumps(msg, protocol=5)
    except Exception:
        data = _cp().dumps(msg, protocol=5)
    return len(data).to_bytes(8, "little") + data


_BACKGROUND_TASKS: set = set()


def spawn_task(coro, loop: Optional[asyncio.AbstractEventLoop] = None
               ) -> "asyncio.Task":
    """create_task + a strong reference until completion.

    The event loop holds only WEAK references to tasks: a fire-and-forget
    task whose only other references sit in its own await chain (task ->
    coroutine frame -> client -> response future -> task_wakeup callback
    -> task) is an unrooted cycle the GC may collect while the task is
    suspended — silently abandoning the work and closing any sockets the
    frame owned.  Every fire-and-forget spawn in this codebase must come
    through here (observed in the wild: task submissions vanishing
    mid-lease under pytest's allocation pattern, surfacing as TCP resets
    from the driver).
    """
    task = (loop or asyncio.get_event_loop()).create_task(coro)
    _BACKGROUND_TASKS.add(task)
    task.add_done_callback(_BACKGROUND_TASKS.discard)
    return task


class RpcServer:
    """Serves named async handlers.  ``handler(payload) -> result``.

    Handlers registered via ``register(name, fn)``; ``fn`` may be a plain
    function or a coroutine function.  Raising inside a handler sends an
    ERROR frame that re-raises at the caller as ``RemoteCallError``.
    """

    def __init__(self, host: Optional[str] = None):
        # Bind and advertise the routable node IP (ref: services.py
        # node_ip_address_from_perspective — round-1 advertised loopback,
        # which cannot span hosts).  Binding the single advertised
        # interface, not 0.0.0.0, limits exposure: frames are
        # cloudpickle-deserialized, so like the reference's gRPC plane
        # this protocol is only safe on a trusted cluster network
        # (RT_BIND_ALL=1 opts into wildcard bind for multi-NIC setups).
        from .net import get_node_ip_address

        if host is not None:
            self._bind_host = self._host = host
        else:
            self._host = get_node_ip_address()
            import os as _os

            self._bind_host = ("0.0.0.0"
                               if _os.environ.get("RT_BIND_ALL") == "1"
                               else self._host)
        self._handlers: Dict[str, Callable[[Any], Awaitable[Any]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: int = 0
        self._conn_lost_cb: Optional[Callable[[str], None]] = None
        self._conns: Dict[str, asyncio.StreamWriter] = {}
        self._conn_counter = itertools.count()
        # Per-method handler latency/inflight (loop-thread only; two
        # attribute writes per dispatch).  Exported as rt_rpc_* by the
        # owning process's metrics tick (util/hotpath.py).
        from ..util.hotpath import RpcStats

        self.stats = RpcStats()

    def register(self, name: str, fn: Callable) -> None:
        self._handlers[name] = fn

    def notify_peer(self, tag: str, method: str, payload: Any) -> bool:
        """Push a NOTIFY frame to a connected peer by its registered
        tag (server -> client direction — the channel streaming task
        results and generator items ride on; the reference's
        equivalent is the worker->owner report RPC stream in
        core_worker.proto).  Returns False when the peer is gone."""
        writer = self._conns.get(tag)
        if writer is None:
            return False
        try:
            writer.write(
                _encode_frame_fast((_NOTIFY, 0, method, payload)))
            return True
        except (ConnectionError, OSError, RuntimeError):
            self._conns.pop(tag, None)
            return False

    def on_connection_lost(self, cb: Callable[[str], None]) -> None:
        """cb(peer_tag) fires when a registered peer's connection drops."""
        self._conn_lost_cb = cb

    def has_peer(self, tag: str) -> bool:
        """Whether a peer with this tag is currently registered (a
        reconnected peer re-registers on its next call)."""
        return tag in self._conns

    async def start(self, port: int = 0) -> int:
        try:
            self._server = await asyncio.start_server(
                self._serve_conn, self._bind_host, port)
        except OSError:
            if self._bind_host in ("0.0.0.0", "127.0.0.1"):
                raise
            # Advertised address not locally bindable (e.g. RT_NODE_IP
            # points at a forwarded/NAT address): fall back to wildcard.
            self._server = await asyncio.start_server(
                self._serve_conn, "0.0.0.0", port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    @property
    def address(self) -> str:
        return f"{self._host}:{self.port}"

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        peer_tag = f"conn-{next(self._conn_counter)}"
        # Reply-write coalescing: responses produced in the same event-
        # loop burst join ONE transport write (a pipelined client would
        # otherwise cost a syscall per reply; the flush runs via
        # call_soon AFTER the currently-ready handler callbacks).
        out_buf: list = []
        out_bytes = [0]
        flush_pending = [False]

        async def _flush():
            flush_pending[0] = False
            if not out_buf:
                return
            data = b"".join(out_buf)
            out_buf.clear()
            out_bytes[0] = 0
            try:
                writer.write(data)
                await writer.drain()
            except (ConnectionError, RuntimeError, OSError) as e:
                logger.debug("srv flush dropped: %r", e)

        loop = asyncio.get_event_loop()

        def send_frame(frame: bytes) -> None:
            out_buf.append(frame)
            out_bytes[0] += len(frame)
            if not flush_pending[0]:
                flush_pending[0] = True
                loop.call_soon(lambda: spawn_task(_flush()))

        async def send_frame_bp(frame: bytes) -> None:
            """send_frame + backpressure: a handler producing bulk
            replies awaits the flush once the coalescing buffer
            swells, so a slow-reading peer bounds memory here instead
            of growing out_buf without limit."""
            send_frame(frame)
            if out_bytes[0] > (8 << 20):
                await _flush()

        try:
            while True:
                try:
                    msg = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                kind, req_id, method, payload = msg
                if kind == _NOTIFY:
                    # Special registration notify lets servers track peers.
                    if method == "__register__":
                        peer_tag = payload
                        self._conns[peer_tag] = writer
                        continue
                    spawn_task(self._dispatch_notify(method, payload))
                    continue
                spawn_task(self._dispatch(method, payload, req_id,
                                          send_frame, send_frame_bp))
        finally:
            # A peer that reconnected re-registered its tag with a NEW
            # writer; when the superseded connection's reader finally
            # errors out, it must neither clobber the live registration
            # nor fire the lost callback (which would, e.g., reclaim a
            # live owner's leases in the node agent).
            cur = self._conns.get(peer_tag)
            superseded = cur is not None and cur is not writer
            if not superseded:
                self._conns.pop(peer_tag, None)
                if self._conn_lost_cb is not None:
                    try:
                        self._conn_lost_cb(peer_tag)
                    except Exception:
                        logger.exception(
                            "connection-lost callback failed")
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch_notify(self, method: str, payload: Any) -> None:
        fn = self._handlers.get(method)
        if fn is None:
            logger.warning("no handler for notify %s", method)
            return
        t0 = self.stats.enter(method)
        try:
            r = fn(payload)
            if asyncio.iscoroutine(r):
                await r
        except Exception:
            logger.exception("notify handler %s failed", method)
        finally:
            self.stats.exit(method, t0)

    async def _dispatch(self, method: str, payload: Any, req_id: int,
                        send_frame, send_frame_bp=None) -> None:
        fn = self._handlers.get(method)
        t0 = self.stats.enter(method)
        try:
            if fn is None:
                raise LookupError(f"no RPC handler {method!r}")
            logger.debug("srv dispatch %s#%d", method, req_id)
            result = fn(payload)
            if asyncio.iscoroutine(result):
                result = await result
            logger.debug("srv reply %s#%d", method, req_id)
            frame = _encode_frame_fast((_RESPONSE, req_id, method,
                                        result))
        except BaseException as e:  # noqa: BLE001 — shipped to caller
            try:
                frame = _encode_frame_fast((_ERROR, req_id, method, e))
            except Exception:
                frame = _encode_frame(
                    (_ERROR, req_id, method, RuntimeError(repr(e))))
        finally:
            self.stats.exit(method, t0)
        try:
            if send_frame_bp is not None and len(frame) > (256 << 10):
                await send_frame_bp(frame)
            else:
                send_frame(frame)
        except (ConnectionError, RuntimeError) as e:
            # Peer went away; the reply has nowhere to go.
            logger.debug("srv reply %s#%d dropped: %r", method, req_id, e)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
            self._server = None


class RpcClient:
    """A multiplexed client connection to one RpcServer.

    All calls share one TCP connection; responses are matched by request
    id.  Not thread-safe by itself — all use goes through the owning
    event loop (see ``EventLoopThread`` for sync callers).
    """

    def __init__(self, address: str, *, tag: str = "",
                 connect_timeout: float = 30.0):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._tag = tag
        self._connect_timeout = connect_timeout
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._req_counter = itertools.count(1)
        self._read_task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()
        self._closed = False
        # Client-side NOTIFY dispatch: the server may push frames at
        # us (stream items, batched results); handlers are plain
        # callables run inline on the read loop — keep them fast.
        self._notify_handlers: Dict[str, Callable[[Any], None]] = {}
        self._disconnect_cbs: list = []
        # Write coalescing (mirror of the server side): frames from
        # one event-loop burst join a single transport write.
        self._out_buf: list = []
        self._flush_pending = False

    def on_notify(self, method: str, fn: Callable[[Any], None]) -> None:
        self._notify_handlers[method] = fn

    def on_disconnect(self, cb: Callable[[], None]) -> None:
        """cb() fires when the connection's read loop ends — the hook
        one-way (notify-based) protocols use to fail their in-flight
        work, since they have no response future to error."""
        self._disconnect_cbs.append(cb)

    async def connect(self) -> None:
        async with self._lock:
            if self._writer is not None or self._closed:
                return
            deadline = asyncio.get_event_loop().time() + self._connect_timeout
            last_err: Optional[Exception] = None
            while asyncio.get_event_loop().time() < deadline:
                try:
                    self._reader, self._writer = await asyncio.open_connection(
                        self._host, self._port)
                    break
                except OSError as e:
                    last_err = e
                    await asyncio.sleep(0.05)
            else:
                raise RpcError(
                    f"cannot connect to {self.address}: {last_err}")
            if self._tag:
                self._writer.write(
                    _encode_frame((_NOTIFY, 0, "__register__", self._tag)))
                await self._writer.drain()
            self._read_task = spawn_task(self._read_loop())

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                kind, req_id, _method, payload = await _read_frame(
                    self._reader)
                logger.debug("cli recv %s#%d <- %s [%x]%s", _method,
                             req_id, self.address, id(self),
                             "" if req_id in self._pending
                             else " (UNMATCHED)")
                if kind == _NOTIFY:
                    fn = self._notify_handlers.get(_method)
                    if fn is not None:
                        try:
                            fn(payload)
                        except Exception:
                            logger.exception(
                                "client notify handler %s failed",
                                _method)
                    continue
                fut = self._pending.pop(req_id, None)
                if fut is None or fut.done():
                    continue
                if kind == _ERROR:
                    fut.set_exception(RemoteCallError(payload)
                                      if not isinstance(payload, RpcError)
                                      else payload)
                else:
                    fut.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("rpc read loop crashed (%s)", self.address)
        finally:
            self._fail_pending(RpcError(f"connection to {self.address} lost"))
            self._writer = None
            self._reader = None
            for cb in self._disconnect_cbs:
                try:
                    cb()
                except Exception:
                    logger.exception("disconnect callback failed")

    def _fail_pending(self, err: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()

    def _write_frame(self, frame: bytes) -> None:
        """Buffered write: the actual transport write happens once per
        event-loop burst (call_soon), so a pipelined burst of calls
        costs one syscall, not one per frame."""
        if self._writer is None:
            raise RpcError(f"not connected to {self.address}")
        self._out_buf.append(frame)
        if not self._flush_pending:
            self._flush_pending = True
            asyncio.get_event_loop().call_soon(
                lambda: self._flush_writes(raise_errors=False))

    def _flush_writes(self, raise_errors: bool = True) -> None:
        self._flush_pending = False
        if not self._out_buf or self._writer is None:
            self._out_buf.clear()
            return
        data = b"".join(self._out_buf)
        self._out_buf.clear()
        try:
            self._writer.write(data)
        except (ConnectionError, OSError, RuntimeError):
            if raise_errors:
                raise
            # Deferred (call_nowait) flush: the read loop notices the
            # dead connection and fails the pending futures.

    async def call(self, method: str, payload: Any = None,
                   timeout: Optional[float] = None) -> Any:
        if self._writer is None:
            await self.connect()
        req_id = next(self._req_counter)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[req_id] = fut
        try:
            logger.debug("cli send %s#%d -> %s [%x]", method, req_id,
                         self.address, id(self))
            self._write_frame(
                _encode_frame((_REQUEST, req_id, method, payload)))
            # Flush NOW so drain applies to THIS frame and write
            # errors surface here (the deferred flush is only for
            # call_nowait pipelining, whose contract is that failures
            # surface via the read loop).
            self._flush_writes()
            await self._writer.drain()
        except (ConnectionError, OSError, AttributeError) as e:
            self._pending.pop(req_id, None)
            raise RpcError(f"send to {self.address} failed: {e}") from e
        if timeout:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    def call_nowait(self, method: str, payload: Any = None
                    ) -> "asyncio.Future":
        """Write a request frame synchronously and return the response
        future.  Unlike ``call``, this never suspends before the write,
        so N ``call_nowait``s made in order put N frames on the wire in
        that order — the guarantee pipelined ordered-actor submission
        is built on (the peer dispatches frames in arrival order).
        Caller must already be connected (``await connect()``)."""
        if self._writer is None:
            raise RpcError(f"not connected to {self.address}")
        req_id = next(self._req_counter)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[req_id] = fut
        try:
            self._write_frame(
                _encode_frame((_REQUEST, req_id, method, payload)))
        except (ConnectionError, OSError) as e:
            self._pending.pop(req_id, None)
            raise RpcError(f"send to {self.address} failed: {e}") from e
        return fut

    def notify_nowait(self, method: str, payload: Any = None) -> None:
        """Synchronous NOTIFY write (coalesced; failures surface via
        the read loop / on_disconnect) — the ordered-actor submission
        path relies on write order == call order."""
        self._write_frame(
            _encode_frame((_NOTIFY, 0, method, payload)))

    async def drain(self) -> None:
        """Apply transport backpressure after call_nowait bursts."""
        if self._writer is not None:
            try:
                await self._writer.drain()
            except (ConnectionError, OSError):
                pass  # the pending futures surface the failure

    async def notify(self, method: str, payload: Any = None) -> None:
        if self._writer is None:
            await self.connect()
        try:
            self._write_frame(
                _encode_frame((_NOTIFY, 0, method, payload)))
            self._flush_writes()
            await self._writer.drain()
        except (ConnectionError, OSError, AttributeError) as e:
            raise RpcError(f"notify to {self.address} failed: {e}") from e

    @property
    def connected(self) -> bool:
        return self._writer is not None

    async def close(self) -> None:
        self._closed = True
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
        self._fail_pending(RpcError("client closed"))


class NotifySideChannel:
    """A lock-guarded blocking socket that writes NOTIFY frames
    straight from the calling thread — no event-loop hop.

    The per-put control notifications (register_object,
    owner_release_local) are tiny fire-and-forget frames, but routing
    them through the io thread costs a call_soon_threadsafe self-pipe
    wakeup that convoys on the GIL with the loop's own work — measured
    at ~0.6 ms per wakeup on a busy driver, dwarfing the 4 MB memcpys
    it accompanies.  Writing the frame here is ~20 µs: encode + one
    sendall into the kernel buffer.  The server treats this like any
    connection; we never read from it (notifies have no replies).

    Delivery ordering holds per channel (one TCP stream); cross-channel
    ordering vs the main RPC connection is NOT guaranteed — only use
    this for notifications that tolerate reordering against call
    traffic (the object plane's pull path polls and re-checks).
    Any failure returns False; the caller falls back to the io-loop
    path (which owns dialing/backoff).
    """

    def __init__(self, address: str,
                 avoid_dial: Optional[Callable[[], bool]] = None):
        self.address = address
        self._sock = None
        self._closed = False
        self._down_until = 0.0
        # Caller-supplied predicate: when true (e.g. running on the
        # io-loop thread via a GC-triggered __del__), never DIAL here —
        # a blocking connect on the loop thread would stall all RPC
        # traffic.  Established-socket sends are bounded and fine.
        self._avoid_dial = avoid_dial
        # RLock + a per-thread busy flag: notify() is reachable from
        # ObjectRef.__del__, so a cyclic-GC run triggered by an
        # allocation INSIDE the locked region (create_connection) can
        # re-enter on the same thread — a plain Lock would self-
        # deadlock.  Re-entrant calls bail to the io-loop fallback.
        self._lock = threading.RLock()
        self._tl = threading.local()

    def notify(self, method: str, payload: Any) -> bool:
        import socket as _socket
        import time as _time

        if self._closed or getattr(self._tl, "busy", False):
            return False  # closed, or re-entered from GC mid-send
        if self._sock is None:
            # Dial backoff: after a failure, fail fast to the io-loop
            # fallback for a beat instead of paying a connect timeout
            # on every release in a burst.
            if _time.monotonic() < self._down_until:
                return False
            if self._avoid_dial is not None and self._avoid_dial():
                return False
        # C pickler: these hot-path payloads are plain dicts of ids —
        # no driver-__main__ objects that need cloudpickle.
        frame = _encode_frame_fast((_NOTIFY, 0, method, payload))
        with self._lock:
            self._tl.busy = True
            try:
                if self._closed:
                    return False
                if self._sock is None:
                    host, port = self.address.rsplit(":", 1)
                    self._sock = _socket.create_connection(
                        (host, int(port)), timeout=2.0)
                    self._sock.setsockopt(_socket.IPPROTO_TCP,
                                          _socket.TCP_NODELAY, 1)
                self._sock.sendall(frame)
                return True
            except OSError:
                try:
                    if self._sock is not None:
                        self._sock.close()
                except OSError:
                    pass
                self._sock = None
                self._down_until = _time.monotonic() + 1.0
                return False
            finally:
                self._tl.busy = False

    def close(self) -> None:
        with self._lock:
            self._closed = True  # latched: a post-shutdown GC'd ref
            if self._sock is not None:  # must never re-dial from here
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class EventLoopThread:
    """A dedicated event-loop thread for synchronous processes (the driver
    and task-executing workers), mirroring how the reference keeps the
    CoreWorker's io_service off the user thread (ref:
    src/ray/core_worker/core_worker.h io_service_)."""

    def __init__(self, name: str = "rt-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run a coroutine on the loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro) -> "asyncio.Future":
        # Route through spawn_task for the strong task reference; the
        # returned concurrent future mirrors run_coroutine_threadsafe.
        import concurrent.futures

        done: "concurrent.futures.Future" = concurrent.futures.Future()

        def _start():
            task = spawn_task(coro, self.loop)

            def _mirror(t):
                if t.cancelled():
                    done.cancel()
                elif t.exception() is not None:
                    done.set_exception(t.exception())
                else:
                    done.set_result(t.result())

            task.add_done_callback(_mirror)

        self.loop.call_soon_threadsafe(_start)
        return done

    def call_soon(self, fn, *args) -> None:
        self.loop.call_soon_threadsafe(fn, *args)

    def stop(self) -> None:
        def _shutdown():
            for task in asyncio.all_tasks(self.loop):
                task.cancel()
            # Defer the stop two cycles so the cancellations unwind first
            # (stopping immediately leaves "Task was destroyed but it is
            # pending" noise at interpreter exit).
            self.loop.call_soon(
                lambda: self.loop.call_soon(self.loop.stop))

        try:
            self.loop.call_soon_threadsafe(_shutdown)
            self._thread.join(timeout=5)
        except Exception:
            pass
        try:
            if not self._thread.is_alive():
                self.loop.close()
        except Exception:
            pass
