"""Resource accounting and TPU accelerator detection.

Role-equivalent to the reference's scheduling resource model plus its
pluggable accelerator managers (ref: src/ray/common/scheduling/,
python/ray/_private/accelerators/tpu.py).  Resources are float-valued named
capacities; "CPU", "TPU", and "memory" are predefined.  TPU detection reads
/dev/accel* and vfio device nodes the way the reference's
TPUAcceleratorManager does, plus JAX-visible device count as a fallback, and
publishes pod/topology extra resources so multi-host slices can gang-schedule
with node affinity.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

CPU = "CPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

_EPS = 1e-9


@dataclass
class ResourceSet:
    """A bag of named float capacities with vector arithmetic."""

    amounts: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self):
        self.amounts = {k: float(v) for k, v in self.amounts.items() if v}

    def get(self, name: str) -> float:
        return self.amounts.get(name, 0.0)

    def is_empty(self) -> bool:
        return not self.amounts

    def covers(self, demand: "ResourceSet") -> bool:
        return all(self.get(k) + _EPS >= v for k, v in demand.amounts.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self.amounts)
        for k, v in other.amounts.items():
            out[k] = out.get(k, 0.0) + v
        return ResourceSet(out)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self.amounts)
        for k, v in other.amounts.items():
            nv = out.get(k, 0.0) - v
            if nv < -_EPS:
                raise ValueError(f"Resource {k} would go negative: {nv}")
            if abs(nv) < _EPS:
                out.pop(k, None)
            else:
                out[k] = nv
        return ResourceSet(out)

    def utilization(self, total: "ResourceSet") -> float:
        """Max fractional usage across resources present in `total`."""
        best = 0.0
        for k, cap in total.amounts.items():
            if cap > 0:
                used = cap - self.get(k)
                best = max(best, used / cap)
        return best

    def copy(self) -> "ResourceSet":
        return ResourceSet(dict(self.amounts))

    def __repr__(self):
        return f"ResourceSet({self.amounts})"


@dataclass
class TPUInfo:
    num_chips: int
    accelerator_type: str  # e.g. "v5e"
    topology: str  # e.g. "2x4"
    pod_name: Optional[str] = None
    worker_id: int = 0


def detect_tpu(override_chips: int = 0) -> Optional[TPUInfo]:
    """Detect local TPU chips.

    Mirrors the detection strategy of the reference's TPUAcceleratorManager
    (ref: python/ray/_private/accelerators/tpu.py:97-110): count /dev/accel*
    or /dev/vfio device nodes, read GCE TPU env/metadata when present.  We
    additionally fall back to a cheap JAX device query only if explicitly
    requested by env (importing jax is expensive for control-plane procs).
    """
    if override_chips:
        chips = override_chips
    else:
        chips = len(glob.glob("/dev/accel*"))
        if chips == 0:
            vfio = glob.glob("/dev/vfio/*")
            chips = len([v for v in vfio if os.path.basename(v).isdigit()])
        if chips == 0 and os.environ.get("RT_TPU_FROM_JAX") == "1":
            try:
                import jax  # noqa: deferred, expensive

                chips = len([d for d in jax.devices() if d.platform == "tpu"])
            except Exception:
                chips = 0
    if chips == 0:
        return None
    accel = os.environ.get("TPU_ACCELERATOR_TYPE", "v5e")
    topology = os.environ.get("TPU_TOPOLOGY", "")
    pod = os.environ.get("TPU_NAME") or os.environ.get("TPU_WORKER_HOSTNAMES")
    worker_id = int(os.environ.get("TPU_WORKER_ID", "0") or 0)
    return TPUInfo(chips, accel, topology, pod, worker_id)


def node_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    memory: Optional[float] = None,
    object_store_memory: Optional[float] = None,
    extra: Optional[Dict[str, float]] = None,
    tpu_override_chips: int = 0,
) -> ResourceSet:
    """Build the resource set a node advertises, with autodetection."""
    amounts: Dict[str, float] = {}
    amounts[CPU] = float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    if num_tpus is not None:
        if num_tpus:
            amounts[TPU] = float(num_tpus)
    else:
        info = detect_tpu(tpu_override_chips)
        if info:
            amounts[TPU] = float(info.num_chips)
            # Pod-level gang-scheduling labels, as resource entries the way the
            # reference exposes TPU-{type}-{topology}-head (ref: tpu.py:230,330).
            if info.topology:
                amounts[f"TPU-{info.accelerator_type}-{info.topology}-head"] = (
                    1.0 if info.worker_id == 0 else 0.0
                )
    if memory is not None:
        amounts[MEMORY] = float(memory)
    if object_store_memory is not None:
        amounts[OBJECT_STORE_MEMORY] = float(object_store_memory)
    if extra:
        amounts.update({k: float(v) for k, v in extra.items()})
    return ResourceSet({k: v for k, v in amounts.items() if v})


def task_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    memory: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    default_cpus: float = 1.0,
) -> ResourceSet:
    amounts: Dict[str, float] = {}
    amounts[CPU] = float(default_cpus if num_cpus is None else num_cpus)
    if num_tpus:
        amounts[TPU] = float(num_tpus)
    if memory:
        amounts[MEMORY] = float(memory)
    if resources:
        amounts.update({k: float(v) for k, v in resources.items()})
    return ResourceSet({k: v for k, v in amounts.items() if v})
