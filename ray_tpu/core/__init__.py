"""Core runtime: IDs, config, serialization, tasks, actors, objects."""

from .config import RuntimeConfig, define_flag, flags  # noqa: F401
from .errors import (ActorDiedError, ActorError, GetTimeoutError,  # noqa: F401
                     ObjectLostError, OutOfMemoryError, RayTpuError,
                     TaskCancelledError, TaskError, WorkerCrashedError)
from .ids import (ActorID, JobID, NodeID, ObjectID,  # noqa: F401
                  PlacementGroupID, TaskID, WorkerID)
from .object_ref import ObjectRef  # noqa: F401
from .resources import ResourceSet, detect_tpu, node_resources  # noqa: F401
from .task import SchedulingStrategy, TaskKind, TaskSpec  # noqa: F401
