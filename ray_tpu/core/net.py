"""Node address detection and advertising.

Role-equivalent to the reference's ray.util.get_node_ip_address (ref:
python/ray/_private/services.py node_ip_address_from_perspective) — every
service binds all interfaces and advertises a routable address so a
cluster can span hosts (round-1 gap: every coordinator advertised
127.0.0.1, which is dead on a real TPU pod).

Resolution order:
1. ``RT_NODE_IP`` env var / ``node_ip`` config flag (explicit operator
   choice, e.g. the ICI-adjacent NIC on a multi-NIC TPU VM).
2. UDP-connect trick: connecting a datagram socket picks the interface
   the kernel would route externally — no packet is sent, so this works
   with zero egress.
3. hostname resolution, skipping loopback.
4. 127.0.0.1 (single-host fallback; everything still works locally).
"""

from __future__ import annotations

import functools
import os
import socket


@functools.lru_cache(maxsize=None)
def _detect_ip() -> str:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 53))
        ip = s.getsockname()[0]
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    finally:
        s.close()
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return "127.0.0.1"


def get_node_ip_address() -> str:
    """The address this node advertises to the rest of the cluster."""
    explicit = os.environ.get("RT_NODE_IP", "").strip()
    if explicit:
        return explicit
    return _detect_ip()


def is_local_address(host: str) -> bool:
    """True if ``host`` names this machine (loopback or our node IP)."""
    if host in ("127.0.0.1", "localhost", "::1", "0.0.0.0", ""):
        return True
    if host == get_node_ip_address():
        return True
    try:
        return socket.gethostbyname(host).startswith("127.")
    except OSError:
        return False


def host_of(address: str) -> str:
    return address.rsplit(":", 1)[0]


def port_of(address: str) -> int:
    return int(address.rsplit(":", 1)[1])


def free_port(host: str = "") -> int:
    """An OS-assigned free TCP port on a local interface (racy by
    nature; callers that can should bind port 0 directly instead)."""
    s = socket.socket()
    s.bind((host if host and is_local_address(host) else "", 0))
    port = s.getsockname()[1]
    s.close()
    return port
