"""Multi-node test cluster on a single machine.

Role-equivalent to the reference's ray.cluster_utils.Cluster (ref:
python/ray/cluster_utils.py:135) — per SURVEY.md §4.2 the single
highest-leverage piece of test infrastructure: N node agents as separate
OS processes sharing one controller, exercising real distributed paths
(spillback scheduling, object transfer, node failure) with no cloud.
"""

from __future__ import annotations

import subprocess
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from .core.config import RuntimeConfig
from .core import node_launcher


@dataclass
class NodeHandle:
    proc: subprocess.Popen
    agent_addr: str
    node_id_hex: str


class Cluster:
    """Start a controller and add/remove node agents programmatically."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None,
                 config: Optional[RuntimeConfig] = None):
        self.config = config or RuntimeConfig.from_env()
        self.session = f"testcluster_{int(time.time()*1000) % 10**10}"
        self._controller_proc, self.address = node_launcher.start_controller(
            self.config, self.session)
        self.nodes: List[NodeHandle] = []
        if initialize_head:
            self.add_node(is_head=True, **(head_node_args or {}))

    @property
    def head_node(self) -> NodeHandle:
        return self.nodes[0]

    def add_node(self, *, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 is_head: bool = False) -> NodeHandle:
        proc, addr, nid = node_launcher.start_node_agent(
            self.config, self.session, self.address,
            num_cpus=num_cpus, num_tpus=num_tpus,
            custom_resources=resources, is_head=is_head,
            tag=f"n{len(self.nodes)}")
        handle = NodeHandle(proc, addr, nid)
        self.nodes.append(handle)
        return handle

    def preempt_node(self, node: NodeHandle,
                     grace_s: float = 3.0) -> None:
        """Preempt a node the way GCP does: SIGTERM (the preemption
        notice — the agent enters DRAINING, training gangs get the
        interruption flag and checkpoint-on-notice), then after
        ``grace_s`` the agent AND its workers are SIGKILLed like the
        VM vanishing.  Blocks for the grace window."""
        from .testing.chaos import preempt_node_processes

        preempt_node_processes(node, grace_s)
        try:
            self.nodes.remove(node)
        except ValueError:
            pass

    def remove_node(self, node: NodeHandle, *,
                    allow_graceful: bool = False) -> None:
        """Kill a node agent (and its workers), simulating node failure."""
        try:
            if allow_graceful:
                node.proc.terminate()
            else:
                node.proc.kill()
            node.proc.wait(timeout=10)
        except Exception:
            pass
        self.nodes.remove(node)

    def kill_controller(self) -> None:
        """SIGKILL the controller (GCS fault injection)."""
        self._controller_proc.kill()
        self._controller_proc.wait(timeout=10)

    def restart_controller(self) -> None:
        """Start a fresh controller on the SAME address/session — the
        GCS-restart scenario (ref: NotifyGCSRestart): with persistence
        on, it reloads its tables and agents/drivers reconnect."""
        from .core.net import port_of

        self._controller_proc, addr = node_launcher.start_controller(
            self.config, self.session, port=port_of(self.address))
        assert addr == self.address, (addr, self.address)

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        """Block until every added node is registered and alive."""
        import ray_tpu

        deadline = time.time() + timeout
        want = {n.node_id_hex for n in self.nodes}
        while time.time() < deadline:
            alive = {n["NodeID"] for n in ray_tpu.nodes() if n["Alive"]}
            if want <= alive:
                return
            time.sleep(0.1)
        raise TimeoutError(f"nodes never came up: {want - alive}")

    def shutdown(self) -> None:
        for node in list(self.nodes):
            try:
                node.proc.kill()
                node.proc.wait(timeout=5)
            except Exception:
                pass
        self.nodes.clear()
        try:
            self._controller_proc.kill()
            self._controller_proc.wait(timeout=5)
        except Exception:
            pass
        # Clean session shm segments.
        import os

        prefix = f"rt_{self.session}_"
        try:
            for name in os.listdir("/dev/shm"):
                if name.startswith(prefix):
                    try:
                        os.unlink(os.path.join("/dev/shm", name))
                    except OSError:
                        pass
        except OSError:
            pass
