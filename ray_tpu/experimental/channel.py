"""Typed channels: pre-negotiated data paths between DAG stages.

Role-equivalent to the reference's channel layer (ref:
python/ray/experimental/channel/shared_memory_channel.py over mutable
plasma objects, C++ experimental_mutable_object_manager.cc).  TPU
framing: host-side stage hand-off is a single-producer single-consumer
ring over ONE shared-memory segment — a write is a memcpy + index bump,
a read is the reverse; no RPC, no scheduler, no pickle-frame per hop.
Device tensors never ride these channels: between chips they move
in-graph over ICI (collectives inside the jitted step), so the channel
plane only carries host metadata and host arrays.

Layout: [u64 write_seq | u64 read_seq | slots x (u64 len | payload)].
SPSC discipline: exactly one producer and one consumer process; seq
counters are monotonic and slot = seq % capacity.  Memory model: the
payload-before-counter ordering relies on TSO (x86) — TPU VM hosts are
x86 — plus double-read counter validation against torn 8-byte updates;
a weakly-ordered host (aarch64) would need the native-atomics path in
src/ (same pattern as shm_pool.cpp) before trusting these rings.
"""

from __future__ import annotations

import pickle
import time
from multiprocessing import shared_memory
from typing import Any, Optional

_HDR = 16  # two u64 sequence counters


class ChannelFull(Exception):
    pass


class ChannelClosed(Exception):
    pass


class Channel:
    """Spec + lazy attach; picklable into actors (ref: ChannelInterface)."""

    def __init__(self, name: str, slot_bytes: int = 1 << 20,
                 num_slots: int = 8, create: bool = False):
        self.name = name
        self.slot_bytes = slot_bytes
        self.num_slots = num_slots
        self._impl: Optional[ShmChannel] = None
        if create:
            ShmChannel(name, slot_bytes, num_slots, create=True).close()

    def _get(self) -> "ShmChannel":
        if self._impl is None:
            self._impl = ShmChannel(self.name, self.slot_bytes,
                                    self.num_slots)
        return self._impl

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        self._get().write(value, timeout)

    def read(self, timeout: Optional[float] = None) -> Any:
        return self._get().read(timeout)

    def close(self) -> None:
        if self._impl is not None:
            self._impl.close()
            self._impl = None

    def exists(self) -> bool:
        """Is the backing segment still linked?  (Loops poll this to
        notice a teardown they missed.)"""
        try:
            seg = shared_memory.SharedMemory(name=self.name)
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(seg._name, "shared_memory")
            except Exception:
                pass
            seg.close()
            return True
        except FileNotFoundError:
            return False

    def destroy(self) -> None:
        self.close()
        ShmChannel.unlink(self.name)

    def __reduce__(self):
        return (Channel, (self.name, self.slot_bytes, self.num_slots))


class ShmChannel:
    """The mapped SPSC ring itself."""

    def __init__(self, name: str, slot_bytes: int, num_slots: int,
                 create: bool = False):
        self.slot_bytes = slot_bytes
        self.num_slots = num_slots
        slot_stride = 8 + slot_bytes
        total = _HDR + num_slots * slot_stride
        if create:
            try:
                self._seg = shared_memory.SharedMemory(
                    name=name, create=True, size=total)
            except FileExistsError:
                # Stale segment from a crashed run: its counters and
                # geometry are untrustworthy — replace it.
                old = shared_memory.SharedMemory(name=name)
                try:
                    from multiprocessing import resource_tracker

                    resource_tracker.unregister(old._name,
                                                "shared_memory")
                except Exception:
                    pass
                old.close()
                old.unlink()
                self._seg = shared_memory.SharedMemory(
                    name=name, create=True, size=total)
            self._seg.buf[:_HDR] = b"\x00" * _HDR
        else:
            self._seg = shared_memory.SharedMemory(name=name)
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(self._seg._name, "shared_memory")
        except Exception:
            pass
        self._stride = slot_stride

    # ------------------------------------------------------------- counters
    def _seq(self, idx: int) -> int:
        # Double-read until stable: the 8-byte counter store is a
        # byte-wise memcpy, so guard against torn reads across a carry.
        while True:
            a = int.from_bytes(self._seg.buf[idx * 8:(idx + 1) * 8],
                               "little")
            b = int.from_bytes(self._seg.buf[idx * 8:(idx + 1) * 8],
                               "little")
            if a == b:
                return a

    def _set_seq(self, idx: int, v: int) -> None:
        self._seg.buf[idx * 8:(idx + 1) * 8] = v.to_bytes(8, "little")

    # ---------------------------------------------------------------- ops
    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        data = pickle.dumps(value, protocol=5)
        if len(data) > self.slot_bytes:
            raise ValueError(
                f"message of {len(data)} bytes exceeds slot size "
                f"{self.slot_bytes}; size the channel for its payloads")
        deadline = time.monotonic() + timeout if timeout is not None else None
        delay = 0.0002
        while True:
            w, r = self._seq(0), self._seq(1)
            if w - r < self.num_slots:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelFull(self._seg.name)
            time.sleep(delay)
            delay = min(delay * 1.5, 0.005)  # idle backoff
        off = _HDR + (w % self.num_slots) * self._stride
        self._seg.buf[off:off + 8] = len(data).to_bytes(8, "little")
        self._seg.buf[off + 8:off + 8 + len(data)] = data
        self._set_seq(0, w + 1)  # publish

    def read(self, timeout: Optional[float] = None) -> Any:
        deadline = time.monotonic() + timeout if timeout is not None else None
        delay = 0.0002
        while True:
            w, r = self._seq(0), self._seq(1)
            if r < w:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self._seg.name} empty")
            time.sleep(delay)
            delay = min(delay * 1.5, 0.005)  # idle backoff
        off = _HDR + (r % self.num_slots) * self._stride
        n = int.from_bytes(self._seg.buf[off:off + 8], "little")
        value = pickle.loads(self._seg.buf[off + 8:off + 8 + n])
        self._set_seq(1, r + 1)  # consume
        return value

    def close(self) -> None:
        try:
            self._seg.close()
        except BufferError:
            pass

    @staticmethod
    def unlink(name: str) -> None:
        try:
            seg = shared_memory.SharedMemory(name=name)
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(seg._name, "shared_memory")
            except Exception:
                pass
            seg.close()
            seg.unlink()
        except FileNotFoundError:
            pass
