"""ray_tpu.experimental — channels for compiled DAGs.

Role-equivalent to the reference's python/ray/experimental/channel/.
"""

from .channel import Channel, ShmChannel  # noqa
