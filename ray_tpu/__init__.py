"""ray_tpu — a TPU-native distributed runtime and ML stack.

A brand-new framework with the capabilities of the reference system
(cloudlounger/ray, surveyed in SURVEY.md): tasks, actors, and an object
plane on a controller/agent/worker runtime, plus jax/XLA-native ML
libraries (collectives, GSPMD parallelism, Train, Data, Tune, Serve, RL).

This top-level module is intentionally import-light: it must not import
jax/flax (worker processes start through it on a 1-core host).  ML
subpackages load lazily on attribute access.
"""

import atexit
import os
from typing import Any, Dict, Optional

from .core import runtime as _runtime_mod
from .core.api import (cancel, get, get_actor, get_runtime_context,  # noqa: F401
                       kill, method, put, remote, wait)
from .core.api import ActorClass, ActorHandle, RemoteFunction  # noqa: F401
from .core.config import RuntimeConfig
from .core.errors import *  # noqa: F401,F403
from .core.object_ref import ObjectRef  # noqa: F401

__version__ = "0.1.0"

_LAZY_SUBMODULES = ("train", "data", "tune", "serve", "rl", "collective",
                    "parallel", "models", "ops", "util")


def __getattr__(name: str):
    if name in _LAZY_SUBMODULES:
        import importlib

        mod = importlib.import_module(f"{__name__}.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def init(
    address: Optional[str] = None,
    *,
    mode: str = "auto",
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    namespace: str = "",
    config: Optional[Dict[str, Any]] = None,
    log_to_driver: Optional[bool] = None,
    ignore_reinit_error: bool = False,
):
    """Start (or connect to) a runtime.

    Role-equivalent to the reference's ray.init (ref:
    python/ray/_private/worker.py:1275).

    - ``mode="local"``: synchronous in-process execution (debugging).
    - ``mode="cluster"``: spawn a controller + node agent + workers on this
      host (the default for ``mode="auto"`` unless RT_LOCAL_MODE=1).
    - ``address="<host:port>"``: connect as a driver to an existing cluster.
    """
    if _runtime_mod.is_initialized():
        if ignore_reinit_error:
            return _runtime_mod.get_runtime()
        raise RuntimeError("ray_tpu.init() called twice "
                           "(pass ignore_reinit_error=True to allow)")
    overrides = dict(config or {})
    if object_store_memory:
        overrides["object_store_memory_bytes"] = int(object_store_memory)
    if log_to_driver is not None:
        overrides["log_to_driver"] = log_to_driver
    cfg = RuntimeConfig.from_env(overrides)
    if address and address.startswith("rt://"):
        # Remote driver: one connection to the head's ClientServer; no
        # cluster-routable agent needed on this machine (ref:
        # util/client/ARCHITECTURE.md).
        from .client.runtime import ClientRuntime

        rt = ClientRuntime(cfg, address[len("rt://"):])
        _runtime_mod.set_runtime(rt)
        atexit.register(_shutdown_quiet)
        return rt
    if mode == "auto":
        import importlib.util

        has_cluster = (
            importlib.util.find_spec("ray_tpu.core.cluster_runtime")
            is not None)
        mode = ("local" if os.environ.get("RT_LOCAL_MODE") == "1"
                or not has_cluster else "cluster")
    if mode == "local":
        from .core.local_runtime import LocalRuntime

        rt = LocalRuntime(cfg)
    elif mode == "cluster":
        from .core.cluster_runtime import ClusterRuntime

        if address == "auto":
            from .scripts.cli import resolve_address

            address = resolve_address(cfg)
            if address is None:
                raise ConnectionError(
                    'address="auto" but no running cluster was found on '
                    "this machine (start one with `python -m ray_tpu "
                    "start --head`).")
        rt = ClusterRuntime(
            cfg, address=address, num_cpus=num_cpus, num_tpus=num_tpus,
            custom_resources=resources, namespace=namespace)
    else:
        raise ValueError(f"Unknown mode {mode!r}")
    _runtime_mod.set_runtime(rt)
    atexit.register(_shutdown_quiet)
    return rt


def _shutdown_quiet():
    try:
        shutdown()
    except Exception:
        pass


def shutdown() -> None:
    """Tear down the runtime started by init()."""
    if _runtime_mod.is_initialized():
        rt = _runtime_mod.get_runtime()
        _runtime_mod.set_runtime(None)
        rt.shutdown()


def is_initialized() -> bool:
    return _runtime_mod.is_initialized()


def cluster_resources() -> Dict[str, float]:
    return _runtime_mod.get_runtime().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _runtime_mod.get_runtime().available_resources()


def nodes():
    return _runtime_mod.get_runtime().nodes()


def timeline(filename: Optional[str] = None):
    """Chrome-trace export of recorded task events (ref: ray.timeline,
    python/ray/_private/state.py:960)."""
    from .util import state as _state

    return _state.timeline(filename)
