"""Sharded crash-atomic checkpoints with reshard-on-restore.

The durability spine of the elastic training plane.  Three properties,
each absent from the msgpack-blob format this replaces:

**Sharded.**  Every rank writes only the array shards its own devices
hold (``shard_<rank>/`` files; jax arrays contribute their
``addressable_shards`` with ``replica_id == 0``, host trees contribute
the slices of the mesh coordinates the rank owns) — there is no rank-0
full-param gather, so checkpoint time and peak host memory stay flat as
the model scales out.

**Crash-atomic.**  All writes land in ``<dir>.tmp/`` and are fsynced;
rank 0 writes ``manifest.json`` (tree structure, per-leaf global
shape/dtype/PartitionSpec, mesh shape, world size, per-file CRCs)
**last**, then commits with a single ``os.replace`` rename.  A SIGKILL
at any instant leaves either the previous committed checkpoint or a
``*.tmp`` directory restore provably ignores — never a torn directory
that restores garbage (the PR-4 checkpoint-on-notice race against the
preemption deadline demands exactly this).

**Reshardable.**  The manifest records where every saved slice of every
leaf lives, so a restore at ANY world size/mesh reads only the slice
intersections each of its devices needs and assembles device arrays
under the new NamedSharding — world N → M works for divisor and
non-divisor pairs alike, which is what lets a preempted v5e slice
resume on whatever capacity the autoscaler found.

Pure slice math lives at the top (unit-testable without devices); jax
imports stay inside functions so non-jax training workers never pay
them.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# On-disk format layer (constants, manifest reading, commit-marker
# discipline, verification) lives jax-free in util/checkpoint_fs so
# the CLI and doctor can use it; re-exported here for API continuity.
from ..util.checkpoint_fs import (FORMAT_VERSION,  # noqa: F401
                                  MANIFEST, OLD_SUFFIX, TMP_SUFFIX,
                                  CheckpointCorruptError,
                                  CheckpointNotCommittedError,
                                  covered_elements, crc32_hex,
                                  is_sharded_checkpoint,
                                  read_manifest, verify_checkpoint)


# ===================================================================
# pure slice math (no jax, unit-testable)
# ===================================================================

def _norm_entry(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(str(a) for a in entry)
    return (str(entry),)


def _spec_entries(spec, ndim: int) -> List[Tuple[str, ...]]:
    entries = [_norm_entry(e) for e in tuple(spec)]
    while len(entries) < ndim:
        entries.append(())
    return entries[:ndim]


def dim_shard_range(dim: int, nshards: int, idx: int
                    ) -> Tuple[int, int]:
    """[start, stop) of shard ``idx`` of a dimension split ``nshards``
    ways — jax's ceil-chunk convention (trailing shards may be short
    or empty when ``nshards`` does not divide ``dim``)."""
    chunk = -(-dim // nshards) if nshards else dim
    start = min(idx * chunk, dim)
    return start, min(start + chunk, dim)


def shard_index(global_shape: Sequence[int], spec,
                axis_sizes: Dict[str, int],
                coord: Dict[str, int]) -> Tuple[Tuple[int, int], ...]:
    """The [start, stop) ranges (one per dim) of the shard a mesh
    coordinate holds under ``spec``.  Multiple axes on one dim compose
    with the FIRST-listed axis slowest-varying (jax convention);
    mesh axes absent from the spec replicate."""
    out = []
    for dim, axes in zip(global_shape,
                         _spec_entries(spec, len(global_shape))):
        nshards = 1
        for a in axes:
            nshards *= axis_sizes.get(a, 1)
        idx = 0
        for a in axes:
            idx = idx * axis_sizes.get(a, 1) + coord.get(a, 0)
        out.append(dim_shard_range(dim, nshards, idx))
    return tuple(out)


def replica_id(spec, global_ndim: int, axis_sizes: Dict[str, int],
               coord: Dict[str, int]) -> int:
    """Linear index of this coordinate among the replicas of its shard
    (over the mesh axes the spec does NOT consume).  The writer
    convention everywhere in this module: only replica 0 writes."""
    used = set()
    for axes in _spec_entries(spec, global_ndim):
        used.update(axes)
    rid = 0
    for a, size in axis_sizes.items():
        if a in used:
            continue
        rid = rid * size + coord.get(a, 0)
    return rid


def enumerate_coords(axis_sizes: Dict[str, int]
                     ) -> List[Dict[str, int]]:
    """All mesh coordinates in C order (first axis slowest)."""
    axes = list(axis_sizes)
    coords: List[Dict[str, int]] = [{}]
    for a in axes:
        coords = [{**c, a: i} for c in coords
                  for i in range(axis_sizes[a])]
    return coords


def coords_for_rank(axis_sizes: Dict[str, int], rank: int,
                    world: int) -> List[Dict[str, int]]:
    """The contiguous block of mesh coordinates rank ``rank`` of
    ``world`` owns (host-mode save: ranks split the flattened mesh)."""
    coords = enumerate_coords(axis_sizes)
    n = len(coords)
    lo = rank * n // world
    hi = (rank + 1) * n // world
    return coords[lo:hi]


def intersect(a: Sequence[Tuple[int, int]],
              b: Sequence[Tuple[int, int]]
              ) -> Optional[Tuple[Tuple[int, int], ...]]:
    """Per-dim intersection of two index ranges, or None if empty —
    the core of reshard-on-restore: a target shard reads exactly the
    overlaps it has with each saved file."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def _ranges_from_slices(index: Tuple, shape: Sequence[int]
                        ) -> Tuple[Tuple[int, int], ...]:
    """Normalize a jax shard ``.index`` (tuple of slices, possibly
    with None bounds) to concrete [start, stop) ranges."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    # 0-d arrays / scalar leaves: index may be shorter than shape.
    for dim in shape[len(out):]:
        out.append((0, dim))
    return tuple(out)


# ===================================================================
# tree naming helpers
# ===================================================================

def _flatten_named(tree) -> List[Tuple[str, Any]]:
    """(slash-joined-name, leaf) pairs.  Plain dict/list/tuple nests
    flatten without jax (non-jax training workers checkpoint numpy
    trees through here); anything else falls back to the jax pytree
    walk (TrainState, optax states, FrozenDict)."""
    try:
        from collections.abc import Mapping

        out: List[Tuple[str, Any]] = []

        def rec(prefix: str, node: Any) -> None:
            if isinstance(node, dict):
                for k in sorted(node, key=str):
                    rec(f"{prefix}/{k}" if prefix else str(k),
                        node[k])
            elif isinstance(node, (list, tuple)):
                for i, v in enumerate(node):
                    rec(f"{prefix}/{i}" if prefix else str(i), v)
            elif hasattr(node, "shape") or \
                    isinstance(node, (int, float, complex, bool,
                                      np.number)):
                out.append((prefix, node))
            else:
                raise TypeError  # FrozenDict/TrainState -> jax walk

        rec("", tree)
        return out
    except TypeError:
        pass
    import jax

    from ..parallel.partition_rules import path_name

    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(path_name(path), leaf) for path, leaf in leaves]


def _spec_map(specs: Any, names: Sequence[str]) -> Dict[str, Any]:
    """name → spec lookup.  Dict spec trees are navigated directly so
    spec leaves may be plain lists/tuples (``["fsdp", None]``) — the
    jax-free form non-jax workers pass; other pytrees (TrainState
    mirrors with PartitionSpec leaves) go through the generic
    flatten."""
    if specs is None:
        return {}
    if isinstance(specs, dict):
        out = {}
        for name in names:
            node: Any = specs
            found = True
            for part in name.split("/"):
                if isinstance(node, dict) and part in node:
                    node = node[part]
                else:
                    found = False
                    break
            if found:
                # An explicit falsy value (None/[]/P()) still counts
                # as PRESENT — it is the deliberate "replicate" spec,
                # distinct from a leaf the dict never mentions.
                out[name] = node
        return out
    return dict(_flatten_named(specs))


def _unflatten_named(pairs: Dict[str, Any]) -> Dict[str, Any]:
    """Rebuild the nested-dict tree from slash-joined leaf names (the
    inverse of ``_flatten_named`` for the dict trees flax produces)."""
    root: Dict[str, Any] = {}
    for name, value in pairs.items():
        parts = name.split("/")
        cur = root
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = value
    return root


# ===================================================================
# low-level file I/O
# ===================================================================

def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class _CrcFile:
    """File-like that CRCs every chunk as ``np.save`` streams it
    through: handed a non-file object, numpy's writer emits bounded
    (~16 MB) chunks, so the checksum comes from the same single pass
    as the write with O(chunk) extra memory — neither re-reading the
    file (doubling save I/O on the preemption-grace-critical path)
    nor buffering the whole serialization (which tripled peak host
    memory per shard)."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.nbytes = 0

    def write(self, data) -> int:
        self._f.write(data)
        self.crc = zlib.crc32(data, self.crc)
        self.nbytes += len(data)
        return len(data)


def _write_array(path: str, arr: np.ndarray) -> Tuple[str, int]:
    """np.save + fsync; returns (crc32 hex, byte size)."""
    with open(path, "wb") as f:
        w = _CrcFile(f)
        np.save(w, arr, allow_pickle=False)
        f.flush()
        os.fsync(f.fileno())
    return format(w.crc & 0xFFFFFFFF, "08x"), w.nbytes


def _read_array(path: str, expect_crc: Optional[str] = None
                ) -> np.ndarray:
    """Validated shard read.  The CRC pass streams in bounded chunks
    and np.load decodes straight from the file (page-cache-warm after
    the CRC pass) — never the whole serialization AND the decoded
    array in memory at once (the read-side twin of _CrcFile)."""
    if expect_crc is not None:
        crc = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 24)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
        got = format(crc & 0xFFFFFFFF, "08x")
        if got != expect_crc:
            raise CheckpointCorruptError(
                f"checksum mismatch for {path}: "
                f"manifest says {expect_crc}, file is {got}")
    with open(path, "rb") as f:
        return np.load(f, allow_pickle=False)


# ===================================================================
# save
# ===================================================================

def _mesh_axis_sizes(mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def _is_jax_sharded(leaf) -> bool:
    return hasattr(leaf, "addressable_shards") and \
        hasattr(getattr(leaf, "sharding", None), "spec")


def save_sharded(path: str, tree: Any, *,
                 specs: Any = None,
                 mesh_axes: Optional[Dict[str, int]] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 meta: Optional[Dict] = None,
                 wait_timeout_s: float = 120.0,
                 save_id: Optional[str] = None) -> Dict[str, Any]:
    """Write this rank's shards of ``tree`` into ``path + ".tmp"``;
    rank 0 waits for every rank's shard index, writes the manifest
    LAST, and commits with ``os.replace(tmp, path)``.

    Two leaf modes, chosen per leaf:

    - **jax arrays** (NamedSharding): each ``addressable_shards`` entry
      with ``replica_id == 0`` is written — the rank ships exactly the
      device-local bytes, never a gathered global array.
    - **host arrays** (numpy): the leaf is the GLOBAL array and
      ``specs``/``mesh_axes``/``process_index``/``process_count``
      describe the layout; the rank writes only the slices of the mesh
      coordinates it owns (replica 0 per leaf).  ``specs=None``
      replicates every leaf (rank 0 writes all of it).

    ``save_id`` is the per-attempt nonce of the two-phase commit:
    every rank of ONE collective save must pass the same value, and it
    must differ between attempts at the same ``path`` (the session
    derives it as ``"<step>:<attempt id>"`` from the driver's
    per-attempt run id).  Rank 0 commits only shard indexes stamped
    with the current nonce, so a re-save of a step whose previous
    attempt was SIGKILLed after some ranks wrote their indexes can
    never merge that attempt's stale shards into the manifest.
    Multi-rank callers outside a session should distribute their own
    nonce; with ``save_id=None`` the stale-index guard degrades to the
    world-size check (a same-world re-save racing a dead attempt's
    leftovers is then indistinguishable until each rank rewrites its
    index).  Single-writer saves (``process_count == 1``) need no
    nonce — the writer clears the whole stale staging dir first.

    Returns ``{"path", "bytes", "files", "committed"}`` for the
    calling rank (``committed`` is True only on the committing rank).
    Crash-consistency contract: ``path`` exists iff the checkpoint is
    complete and validated-writable; anything else is a ``*.tmp``
    directory restore ignores.
    """
    from contextlib import nullcontext

    from ..util import goodput

    # Inside a checkpoint-on-notice block the OUTER phase owns the
    # wall-clock (the drain plane measures exactly that race); only a
    # periodic save enters the plain checkpoint phase itself.
    phase_cm = (nullcontext()
                if goodput.current_phase() == "checkpoint_on_notice"
                else goodput.ledger().phase("checkpoint"))
    t0 = time.monotonic()
    with phase_cm:
        result = _save_sharded_inner(
            path, tree, specs=specs, mesh_axes=mesh_axes,
            process_index=process_index, process_count=process_count,
            meta=meta, wait_timeout_s=wait_timeout_s,
            save_id=save_id)
    _observe_save(result, time.monotonic() - t0)
    return result


def _observe_save(result: Dict[str, Any], dt: float) -> None:
    try:
        from ..util.metrics import Gauge, Histogram

        Histogram("rt_train_checkpoint_save_seconds",
                  "Checkpoint payload save/restore duration.",
                  tag_keys=("sharded",)).observe(
            dt, tags={"sharded": "1"})
        Gauge("rt_checkpoint_bytes",
              "Bytes this process wrote into its most recent "
              "checkpoint save.").set(float(result["bytes"]))
        Gauge("rt_checkpoint_shards",
              "Shard files this process wrote into its most recent "
              "checkpoint save.").set(float(result["files"]))
    except Exception:
        pass  # telemetry must never fail a save


def _save_sharded_inner(path: str, tree: Any, *, specs, mesh_axes,
                        process_index, process_count, meta,
                        wait_timeout_s, save_id) -> Dict[str, Any]:
    final = os.path.abspath(path)
    tmp = final + TMP_SUFFIX
    named = _flatten_named(tree)
    spec_by_name = _spec_map(specs, [n for n, _l in named])

    jax_mode = any(_is_jax_sharded(leaf) for _n, leaf in named)
    if process_index is None:
        if jax_mode:
            import jax

            process_index = jax.process_index()
            process_count = jax.process_count()
        else:
            process_index, process_count = 0, 1
    process_count = process_count or 1

    if jax_mode and mesh_axes is None:
        for _n, leaf in named:
            if _is_jax_sharded(leaf):
                mesh_axes = _mesh_axis_sizes(leaf.sharding.mesh)
                break
    mesh_axes = dict(mesh_axes or {"data": process_count})
    my_coords = coords_for_rank(mesh_axes, process_index,
                                process_count)

    shard_dir = os.path.join(tmp, f"shard_{process_index}")
    if process_count == 1 and process_index == 0:
        # Single writer: wipe the WHOLE stale staging dir — a crashed
        # previous attempt (any world size) can have left complete
        # shard dirs + indexes there, and nobody else is writing.
        shutil.rmtree(tmp, ignore_errors=True)
    else:
        # A crashed previous attempt may have left MY stale shard dir
        # in the shared tmp; replacing only our own keeps ranks from
        # racing each other's writes.  Stale PEER shard dirs are
        # handled at commit: rank 0 only accepts indexes stamped with
        # the current save_id/world (see _commit).
        shutil.rmtree(shard_dir, ignore_errors=True)
    os.makedirs(shard_dir, exist_ok=True)

    entries: List[Dict[str, Any]] = []
    leaf_meta: Dict[str, Dict[str, Any]] = {}
    counter = 0
    total_bytes = 0

    from ..parallel.partition_rules import spec_to_json

    for name, leaf in named:
        if _is_jax_sharded(leaf):
            spec = leaf.sharding.spec
            shape = tuple(int(d) for d in leaf.shape)
            dtype = np.dtype(leaf.dtype).name
            shards = [(tuple(_ranges_from_slices(s.index, shape)),
                       s.data) for s in leaf.addressable_shards
                      if s.replica_id == 0]
        else:
            arr = np.asarray(leaf)
            if name in spec_by_name:
                spec = spec_by_name[name] or ()
            elif isinstance(specs, dict) and arr.ndim:
                # A leaf silently absent from an EXPLICIT specs dict
                # (typo'd key, renamed param) would fall back to
                # replicated — i.e. a rank-0 full write, the exact
                # gather this plane exists to avoid.  Require an
                # explicit []/None to replicate.  (Dict specs only:
                # non-dict pytree mirrors drop None/empty markers
                # during flattening, so absence there is the normal
                # replicate convention; specs=None keeps the
                # replicate-everything default; scalars always
                # replicate.)
                raise ValueError(
                    f"leaf {name!r} has no entry in the given specs "
                    f"dict — pass an explicit [] (replicate) or a "
                    f"partition spec for every non-scalar host leaf")
            else:
                # () == replicate: jax-free default so non-jax
                # workers never import jax.sharding just to say
                # "unsharded".
                spec = ()
            for axes in _spec_entries(spec, arr.ndim):
                for a in axes:
                    if a not in mesh_axes:
                        # Silently treating an unknown axis as size 1
                        # would quietly collapse to rank-0-writes-
                        # everything — the exact gather this plane
                        # exists to avoid.
                        raise ValueError(
                            f"leaf {name!r}: spec names mesh axis "
                            f"{a!r} absent from mesh_axes "
                            f"{sorted(mesh_axes)} — pass mesh_axes "
                            f"covering every spec axis")
            shape = arr.shape
            dtype = arr.dtype.name
            seen = set()
            shards = []
            for coord in my_coords:
                if replica_id(spec, arr.ndim, mesh_axes, coord):
                    continue
                ranges = shard_index(shape, spec, mesh_axes, coord)
                if ranges in seen:
                    continue
                if any(lo >= hi for lo, hi in ranges) and arr.ndim:
                    continue  # empty trailing shard (non-divisor dim)
                seen.add(ranges)
                view = arr[tuple(slice(lo, hi) for lo, hi in ranges)]
                shards.append((ranges, view))
        leaf_meta[name] = {"shape": list(shape), "dtype": dtype,
                           "spec": spec_to_json(spec)}
        for ranges, data in shards:
            fname = f"arr_{counter:05d}.npy"
            counter += 1
            crc, size = _write_array(os.path.join(shard_dir, fname),
                                     np.asarray(data))
            total_bytes += size
            entries.append({
                "leaf": name,
                "file": f"shard_{process_index}/{fname}",
                "index": [list(r) for r in ranges],
                "crc32": crc, "bytes": size,
                "rank": process_index})

    from ..util.checkpoint_fs import atomic_write

    atomic_write(os.path.join(shard_dir, "index.json"),
                 json.dumps({"rank": process_index,
                             "world": process_count,
                             "save_id": save_id,
                             "entries": entries,
                             "leaves": leaf_meta}))
    _fsync_dir(shard_dir)

    committed = False
    if process_index == 0:
        _commit(tmp, final, mesh_axes, process_count, meta,
                wait_timeout_s, save_id)
        committed = True
    return {"path": final, "bytes": total_bytes, "files": counter,
            "committed": committed}


def _read_index(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # mid-replace / vanished: treat as not yet there


def _index_stale(idx: Optional[Dict], world: int,
                 save_id: Optional[str]) -> Optional[str]:
    """Why this shard index cannot belong to the CURRENT save attempt
    (None if it can).  The guard against committing a manifest that
    mixes a SIGKILLed previous attempt's complete-looking indexes with
    the current attempt's shards — their CRCs self-validate, so
    nothing downstream would catch it."""
    if idx is None:
        return "missing"
    if idx.get("world") != world:
        return (f"stale (written at world {idx.get('world')}, "
                f"this save is world {world})")
    if save_id is not None and idx.get("save_id") != save_id:
        return (f"stale (save_id {idx.get('save_id')!r}, this save "
                f"is {save_id!r})")
    return None


def _commit(tmp: str, final: str, mesh_axes: Dict[str, int],
            world: int, meta: Optional[Dict],
            wait_timeout_s: float,
            save_id: Optional[str] = None) -> None:
    """Rank 0's half of the two-phase commit: wait for every rank's
    shard index TO CARRY THE CURRENT ATTEMPT'S STAMP (save_id +
    world — mere existence is not enough: a previous SIGKILLed
    attempt of the same step leaves complete stale indexes in the
    shared staging dir until each rank's re-save replaces its own),
    merge them into the manifest, fsync, rename."""
    deadline = time.monotonic() + wait_timeout_s
    index_paths = [os.path.join(tmp, f"shard_{r}", "index.json")
                   for r in range(world)]
    # An index that validated once cannot turn stale (its rank will
    # not rewrite it within the attempt) — cache acceptances so each
    # poll tick re-reads only the still-pending ranks, not all of
    # them (matters at large world on shared storage).
    accepted: Dict[str, Dict] = {}
    while True:
        pending = {}
        for p in index_paths:
            if p in accepted:
                continue
            idx = _read_index(p)
            why = _index_stale(idx, world, save_id)
            if why is not None:
                pending[p] = why
            else:
                accepted[p] = idx
        if not pending:
            break
        if time.monotonic() > deadline:
            detail = "; ".join(
                f"{os.path.basename(os.path.dirname(p))}: {why}"
                for p, why in pending.items())
            raise TimeoutError(
                f"sharded save: shard index(es) not written by their "
                f"rank for this attempt within {wait_timeout_s}s "
                f"({detail}); NOT committing {final}")
        time.sleep(0.05)

    files: List[Dict] = []
    leaves: Dict[str, Dict] = {}
    for p in index_paths:
        idx = accepted[p]
        files.extend(idx.get("entries", []))
        for name, m in (idx.get("leaves") or {}).items():
            leaves.setdefault(name, m)
    # Leftover shard dirs beyond this save's world (an elastic shrink
    # re-saving over a bigger dead attempt) are not in the manifest —
    # drop them so they don't ride into the committed dir as garbage.
    try:
        for name in os.listdir(tmp):
            if not name.startswith("shard_"):
                continue
            try:
                rank = int(name[len("shard_"):])
            except ValueError:
                continue
            if rank >= world:
                shutil.rmtree(os.path.join(tmp, name),
                              ignore_errors=True)
    except OSError:
        pass
    manifest = {
        "version": FORMAT_VERSION,
        "world_size": world,
        "mesh": {"axes": list(mesh_axes), "shape": dict(mesh_axes)},
        "leaves": leaves,
        "files": files,
        "meta": meta or {},
        "ts": time.time(),
    }
    from ..util.checkpoint_fs import atomic_write

    atomic_write(os.path.join(tmp, MANIFEST), json.dumps(manifest))
    _fsync_dir(tmp)
    if os.path.isdir(final):
        # A committed checkpoint already holds this name (a re-save of
        # the same step after a restart): swap by renaming it aside,
        # then renaming the new copy in.  The aside name keeps the
        # .tmp suffix so a crash mid-swap leaves a directory every
        # reader (is_committed/find_latest_in/scan_run_dir) already
        # ignores, not a stale twin that outsorts the real one.
        # Known window: a crash BETWEEN the two os.replace calls
        # leaves no committed copy under this name — resume falls back
        # to an older committed checkpoint (never corruption), and the
        # good copy survives at the aside name, which scan_run_dir
        # marks ``recoverable`` and ``rt doctor`` tells the operator
        # to rename back.
        old = final + OLD_SUFFIX
        shutil.rmtree(old, ignore_errors=True)
        os.replace(final, old)
        os.replace(tmp, final)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.replace(tmp, final)  # THE commit point
    _fsync_dir(os.path.dirname(final))


# ===================================================================
# restore
# ===================================================================

def _assemble(shape, dtype, ranges, file_entries, base_dir,
              validate: bool, cache: Dict[str, np.ndarray]
              ) -> np.ndarray:
    """Fill a [ranges]-shaped array from the intersections the saved
    files contribute — the reshard read path."""
    out = np.empty([hi - lo for lo, hi in ranges], dtype=dtype)
    inters = []
    for ent in file_entries:
        src_ranges = tuple(tuple(r) for r in ent["index"])
        inter = intersect(ranges, src_ranges)
        if inter is None:
            continue
        fpath = os.path.join(base_dir, ent["file"])
        arr = cache.get(ent["file"])
        if arr is None:
            if not os.path.exists(fpath):
                raise CheckpointCorruptError(
                    f"manifest names missing shard file {fpath}")
            arr = _read_array(
                fpath, ent.get("crc32") if validate else None)
            cache[ent["file"]] = arr
        dst = tuple(slice(lo - r[0], hi - r[0])
                    for (lo, hi), r in zip(inter, ranges))
        src = tuple(slice(lo - r[0], hi - r[0])
                    for (lo, hi), r in zip(inter, src_ranges))
        out[dst] = arr[src]
        inters.append(inter)
    want = int(np.prod([hi - lo for lo, hi in ranges])) if ranges \
        else 1
    # UNION coverage (interval arithmetic), never summed volumes:
    # overlapping saved slices occur exactly in the malformed-manifest
    # cases this backstop exists for, and a sum would let them mask an
    # np.empty-garbage hole.
    filled = covered_elements(ranges, inters)
    if filled < want:
        raise CheckpointCorruptError(
            f"saved shards cover only {filled}/{want} elements of "
            f"requested slice {ranges} — incomplete checkpoint")
    return out


def load_sharded(path: str, *, mesh=None, specs: Any = None,
                 target: Any = None, validate: bool = True
                 ) -> Any:
    """Restore a sharded checkpoint, resharding onto ``mesh``.

    - ``mesh=None``: assemble full host (numpy) arrays — the
      degenerate world-1 restore.
    - ``mesh`` given: each leaf becomes a jax array under
      ``NamedSharding(mesh, spec)`` where ``spec`` comes from
      ``specs`` (a pytree matching the checkpoint's structure) or,
      by default, the SAVED spec pruned to the new mesh's axes.  Each
      addressable device reads only the slice intersections it needs
      from the manifest's layout — no full-array materialization
      unless a device genuinely needs the full array.
    - ``target``: map restored leaves onto this tree's structure
      (names must match); also coerces restored values into the
      target's leaf positions for optimizer-state trees.

    ``validate`` checks the CRC of every shard file actually read;
    a mismatch raises :class:`CheckpointCorruptError`.
    """
    from ..util import goodput

    with goodput.timed_phase(
            "checkpoint", "rt_train_checkpoint_restore_seconds",
            "Checkpoint payload save/restore duration.",
            tags={"sharded": "1"}, tag_keys=("sharded",)):
        return _load_sharded_inner(path, mesh=mesh, specs=specs,
                                   target=target, validate=validate)


def _load_sharded_inner(path, *, mesh, specs, target, validate):
    manifest = read_manifest(path)
    by_leaf: Dict[str, List[Dict]] = {}
    for ent in manifest.get("files", []):
        by_leaf.setdefault(ent["leaf"], []).append(ent)

    spec_by_name = _spec_map(specs,
                             list(manifest.get("leaves") or {}))

    restored: Dict[str, Any] = {}
    for name, info in manifest.get("leaves", {}).items():
        shape = tuple(info["shape"])
        dtype = np.dtype(info["dtype"])
        entries = by_leaf.get(name, [])
        cache: Dict[str, np.ndarray] = {}
        full = tuple((0, d) for d in shape)
        if mesh is None:
            restored[name] = _assemble(shape, dtype, full, entries,
                                       path, validate, cache)
            continue
        import jax
        from jax.sharding import NamedSharding

        from ..parallel.partition_rules import (prune_spec,
                                                spec_from_json)

        sizes = _mesh_axis_sizes(mesh)
        spec = spec_by_name.get(name)
        if spec is None:
            spec = spec_from_json(info.get("spec"))
        spec = prune_spec(spec, sizes)
        sharding = NamedSharding(mesh, spec)
        imap = sharding.devices_indices_map(shape)
        pieces: Dict[Tuple, np.ndarray] = {}
        arrays = []
        for dev, index in imap.items():
            if dev.process_index != jax.process_index():
                continue
            ranges = _ranges_from_slices(index, shape)
            piece = pieces.get(ranges)
            if piece is None:
                piece = _assemble(shape, dtype, ranges, entries,
                                  path, validate, cache)
                pieces[ranges] = piece
            arrays.append(jax.device_put(piece, dev))
        restored[name] = jax.make_array_from_single_device_arrays(
            shape, sharding, arrays)

    if target is None:
        return _unflatten_named(restored)

    from ..parallel.partition_rules import named_tree_map

    def _pick(name: str, leaf):
        if name not in restored:
            raise CheckpointCorruptError(
                f"checkpoint {path} has no leaf {name!r} the target "
                f"tree expects")
        return restored[name]

    return named_tree_map(_pick, target)
