"""Train v2 — elastic controller with pluggable scaling/failure policies.

Role-equivalent to the reference's Train v2 control loop (ref:
train/v2/_internal/execution/controller.py:73 TrainController state
machine, loop at :276,325, with pluggable ScalingPolicy/FailurePolicy).
TPU framing: the worker gang IS one SPMD program, so elasticity is
whole-group — each attempt re-decides the gang size from what the
cluster can actually schedule, re-initializes jax.distributed at that
size, and resumes from the latest checkpoint (a TPU slice is the atomic
failure domain; per-worker patching is not meaningful under SPMD).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, replace
from enum import Enum
from typing import Any, Callable, Dict, List, Optional

import ray_tpu

from .checkpoint import CheckpointManager
from .config import Result
from .trainer import BaseTrainer, JaxBackend
from .worker_group import (DETERMINISTIC_ERRORS, PreemptionError,
                           WorkerGroupError)


class ControllerState(str, Enum):
    INITIALIZING = "INITIALIZING"
    SCHEDULING = "SCHEDULING"
    RUNNING = "RUNNING"
    RESTARTING = "RESTARTING"
    RESIZING = "RESIZING"
    ERRORED = "ERRORED"
    FINISHED = "FINISHED"


class ScalingPolicy:
    """Decides the gang size for the next attempt."""

    def workers_for_attempt(self, attempt: int) -> int:
        raise NotImplementedError


@dataclass
class FixedScalingPolicy(ScalingPolicy):
    num_workers: int = 1

    def workers_for_attempt(self, attempt: int) -> int:
        return self.num_workers


@dataclass
class ElasticScalingPolicy(ScalingPolicy):
    """Size the gang to what the cluster can schedule NOW, clamped to
    [min_workers, max_workers], by the gang's ACTUAL per-worker
    resource shape — TPU chips, slice labels, custom resources, CPU —
    whichever is the binding constraint (ref: v2 ScalingPolicy elastic
    recovery + controller.py:73; round-2 weak item 3: sizing by CPU
    alone made TPU gang resizes ignore chips entirely).

    TPU slice atomicity: with ``workers_per_slice > 1`` (one SPMD
    worker per slice host), the gang size snaps DOWN to a whole number
    of slices — a partial slice can't run the compiled program (SURVEY
    §7 stage 9 slice-granular elasticity).
    """

    min_workers: int = 1
    max_workers: int = 8
    # Per-worker resource demand; None = {"CPU": 1}.
    resources_per_worker: Optional[Dict[str, float]] = None
    workers_per_slice: int = 1

    @classmethod
    def from_scaling_config(cls, cfg, *, min_workers: int = 1,
                            max_workers: Optional[int] = None,
                            workers_per_slice: int = 1
                            ) -> "ElasticScalingPolicy":
        """Derive the resize shape from the trainer's ScalingConfig so
        the elastic gang resizes by what its workers really consume."""
        return cls(min_workers=min_workers,
                   max_workers=max_workers or cfg.num_workers,
                   resources_per_worker=cfg.worker_resources(),
                   workers_per_slice=workers_per_slice)

    def workers_for_attempt(self, attempt: int) -> int:
        shape = {k: v for k, v in
                 (self.resources_per_worker or {"CPU": 1.0}).items()
                 if v > 0}
        try:
            avail = ray_tpu.available_resources()
        except Exception:
            avail = {}
        fit = min((int(avail.get(k, 0.0) // v)
                   for k, v in shape.items()),
                  default=0) if shape else 0
        if self.workers_per_slice > 1:
            fit -= fit % self.workers_per_slice
        return max(self.min_workers, min(self.max_workers, fit))


class FailureDecision(str, Enum):
    RETRY = "RETRY"
    RAISE = "RAISE"


# See worker_group.DETERMINISTIC_ERRORS for the rationale (shared with
# the trainer's announced-failure classification).
_DETERMINISTIC_ERRORS = DETERMINISTIC_ERRORS


@dataclass
class FailurePolicy:
    """ref: v2 FailurePolicy — bounded retries, but with error
    classification: deterministic user-code exceptions RAISE
    immediately, and announced preemptions always RETRY (budget
    accounting for those lives in the controller)."""

    max_failures: int = 3

    def decide(self, failure_count: int,
               error: BaseException) -> FailureDecision:
        if isinstance(error, PreemptionError):
            # Announced failure: retrying is the whole point of the
            # drain plane, and it costs no budget.
            return FailureDecision.RETRY
        if isinstance(error, _DETERMINISTIC_ERRORS):
            return FailureDecision.RAISE
        if self.max_failures < 0:  # infinite retries
            return FailureDecision.RETRY
        return (FailureDecision.RETRY
                if failure_count <= self.max_failures
                else FailureDecision.RAISE)


# Shared with the serve resilience plane's circuit breakers; lives in a
# jax-free util module now (importing through ray_tpu.train pulls
# jax/optax, which serve proxies must never pay for).
from ..util.backoff import RestartBackoff  # noqa: F401,E402


class TrainControllerV2:
    """Drives attempts of a BaseTrainer-compatible trainer through the
    v2 state machine; exposes the state transitions for observability
    (ref: controller.py TrainControllerStateType)."""

    def __init__(self, trainer: BaseTrainer,
                 scaling_policy: Optional[ScalingPolicy] = None,
                 failure_policy: Optional[FailurePolicy] = None,
                 restart_backoff: Optional[RestartBackoff] = None):
        self.trainer = trainer
        self.scaling_policy = scaling_policy or FixedScalingPolicy(
            trainer.scaling_config.num_workers)
        self.failure_policy = failure_policy or FailurePolicy(
            trainer.run_config.failure_config.max_failures)
        self.restart_backoff = restart_backoff or \
            RestartBackoff.from_config()
        self.state_history: List[Dict[str, Any]] = []
        self.attempt_sizes: List[int] = []
        self.backoff_delays: List[float] = []   # observed (tests/ops)
        self.announced_failures = 0             # preemptions absorbed
        self._restarting = False

    def _transition(self, state: ControllerState, **info) -> None:
        self.state_history.append(
            {"state": state.value, "ts": time.time(), **info})
        from ..util import flight_recorder

        flight_recorder.record("train_state", state=state.value, **info)

    def _mark_restart(self, active: bool) -> None:
        """Attribute the gang-down window (failure detected -> next
        attempt launches) to the ``restart`` goodput phase."""
        from ..util import goodput

        if active and not self._restarting:
            goodput.ledger().enter("restart")
            self._restarting = True
        elif not active and self._restarting:
            goodput.ledger().exit()
            self._restarting = False

    def fit(self) -> Result:
        import os

        from ..util import flight_recorder

        run_dir = self.trainer.run_config.resolved_storage_path()
        flight_recorder.install(
            dump_dir=os.path.join(run_dir, "flight"),
            source=f"driver-{os.getpid()}")
        self._transition(ControllerState.INITIALIZING)
        ckpt_cfg = self.trainer.run_config.checkpoint_config
        manager = CheckpointManager(
            run_dir, num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order)
        start_ckpt = self.trainer.resume_from_checkpoint or \
            CheckpointManager.find_latest_in(run_dir)
        history: List[Dict] = []
        failures = 0
        attempt = 0
        try:
            return self._fit_loop(manager, start_ckpt, history,
                                  failures, attempt, run_dir)
        finally:
            # A raise during the next attempt's scheduling (or any
            # abort) must not leave the process-global ledger stuck
            # in the restart phase forever.
            self._mark_restart(False)

    def _fit_loop(self, manager, start_ckpt, history, failures,
                  attempt, run_dir) -> Result:
        while True:
            self._transition(ControllerState.SCHEDULING,
                             attempt=attempt)
            size = max(1, self.scaling_policy.workers_for_attempt(
                attempt))
            prev = self.trainer.scaling_config.num_workers
            if size != prev and attempt > 0:
                # A sharded checkpoint reshards transparently onto the
                # new world; surface the N→M hop (and the saved mesh)
                # in the state history so an elastic resize is
                # attributable after the fact.
                info = {}
                if start_ckpt is not None:
                    try:
                        from .sharded_checkpoint import read_manifest

                        man = read_manifest(start_ckpt.path)
                        info = {"ckpt_world": man.get("world_size"),
                                "ckpt_mesh": (man.get("mesh") or
                                              {}).get("shape")}
                    except Exception:
                        pass
                self._transition(ControllerState.RESIZING,
                                 from_workers=prev, to_workers=size,
                                 **info)
            self.trainer.scaling_config = replace(
                self.trainer.scaling_config, num_workers=size)
            self.attempt_sizes.append(size)
            self._transition(ControllerState.RUNNING, workers=size)
            self._mark_restart(False)
            t_attempt = time.time()
            try:
                final = self.trainer._run_attempt(manager, start_ckpt,
                                                  history)
                self._transition(ControllerState.FINISHED)
                return Result(metrics=final,
                              checkpoint=manager.latest(),
                              path=run_dir, metrics_history=history)
            except WorkerGroupError as e:
                if time.time() - t_attempt > self.restart_backoff.max_s:
                    # A long-lived attempt means the cluster was
                    # healthy again; don't punish a fresh incident
                    # with the tail of the previous one's schedule.
                    self.restart_backoff.reset()
                announced = isinstance(e.cause, PreemptionError)
                if announced:
                    # An ANNOUNCED failure (drain/preemption notice
                    # preceded the death): the gang already raced a
                    # checkpoint-on-notice, so the restart resumes
                    # from it — and it costs no max_failures slot,
                    # because preemption frequency is a property of
                    # the (spot) fleet, not of the user's job.
                    self.announced_failures += 1
                else:
                    failures += 1
                decision = self.failure_policy.decide(failures, e.cause)
                if decision == FailureDecision.RAISE:
                    self._transition(ControllerState.ERRORED,
                                     error=repr(e.cause),
                                     failures=failures)
                    return Result(
                        metrics=history[-1]["metrics"] if history
                        else {},
                        checkpoint=manager.latest(), path=run_dir,
                        error=e.cause, metrics_history=history)
                self._transition(ControllerState.RESTARTING,
                                 failures=failures,
                                 announced=announced)
                self._mark_restart(True)
                # Jittered exponential backoff between attempts: the
                # old hot-loop retry re-failed instantly during
                # incidents and synchronized restarts fleet-wide
                # after a preemption wave.  The wait is restart
                # downtime, so it accrues to the ``restart`` goodput
                # phase entered just above.
                delay = self.restart_backoff.next_delay()
                if delay > 0:
                    self.backoff_delays.append(delay)
                    self._transition(ControllerState.RESTARTING,
                                     backoff_s=round(delay, 3))
                    time.sleep(delay)
                start_ckpt = manager.latest()
                attempt += 1


class JaxTrainerV2:
    """User-facing v2 trainer: JaxTrainer semantics under the elastic
    controller."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict] = None,
                 scaling_policy: Optional[ScalingPolicy] = None,
                 failure_policy: Optional[FailurePolicy] = None,
                 run_config=None, datasets=None, scaling_config=None,
                 resume_from_checkpoint=None, backend_cls=JaxBackend):
        from .config import ScalingConfig

        # num_workers is decided per attempt by the scaling policy;
        # the rest of the ScalingConfig (worker_env, resource shape,
        # placement) carries through every attempt via dataclasses
        # .replace in the controller.
        trainer = BaseTrainer(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            scaling_config=scaling_config or ScalingConfig(
                num_workers=1),
            run_config=run_config, datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
        trainer.backend_cls = backend_cls
        self.controller = TrainControllerV2(
            trainer, scaling_policy=scaling_policy,
            failure_policy=failure_policy)

    def fit(self) -> Result:
        return self.controller.fit()

    @property
    def state_history(self) -> List[Dict[str, Any]]:
        return self.controller.state_history
