"""ray_tpu.train — the Train stack (JaxTrainer, worker groups, sessions).

Role-equivalent to the reference's ray.train (ref: SURVEY.md §2.4).  The
low-level pure-function training step lives in train_step.py; the
actor-based trainer stack (WorkerGroup/BackendExecutor/JaxTrainer) builds
on the cluster runtime.
"""

from .train_step import (TrainState, make_optimizer,  # noqa: F401
                         make_sharded_train_step, make_train_step)
from .distributed import (DistributedMesh, derive_mesh_shape,  # noqa
                          global_batch_slice, mesh_coords_for_rank,
                          put_global_batch, rules_for_model,
                          setup_distributed_mesh, shard_train_state)
from .checkpoint import Checkpoint, CheckpointManager  # noqa: F401
from .config import (CheckpointConfig, FailureConfig, Result,  # noqa
                     RunConfig, ScalingConfig, TelemetryConfig)
from .session import (checkpoint_dir, checkpoint_on_notice,  # noqa
                      data_wait, get_checkpoint, get_dataset_shard,
                      get_local_rank, get_world_rank, get_world_size,
                      interrupted, interruption, iter_device_batches,
                      load_sharded_checkpoint, report,
                      save_sharded_checkpoint)
from .sharded_checkpoint import (load_sharded,  # noqa: F401
                                 save_sharded, verify_checkpoint)
from .trainer import (DataParallelTrainer, JaxTrainer,  # noqa: F401
                      TorchTrainer)
from .worker_group import PreemptionError, WorkerGroup  # noqa: F401
from .v2 import (ControllerState, ElasticScalingPolicy,  # noqa: F401
                 FailureDecision, FailurePolicy, FixedScalingPolicy,
                 JaxTrainerV2, RestartBackoff, TrainControllerV2)
