"""Train-stack configuration dataclasses.

Role-equivalent to the reference's air config surface (ref:
python/ray/air/config.py ScalingConfig/RunConfig/FailureConfig/
CheckpointConfig, python/ray/train/_checkpoint.py).  TPU-era default: a
worker is one TPU *host* (use_tpu implies chips-per-worker resources and
STRICT_SPREAD gang placement so worker == jax process).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # Environment applied to every gang worker BEFORE the backend
    # bootstrap hook runs (i.e. before the worker's first jax import)
    # — the supported way to set process-level XLA knobs like
    # XLA_FLAGS=--xla_force_host_platform_device_count=N for the CPU
    # multi-process CI mesh, or libtpu tuning flags in production.
    worker_env: Optional[Dict[str, str]] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = float(os.environ.get("RT_TPU_PER_WORKER", 4))
        return res


@dataclass
class FailureConfig:
    max_failures: int = 0


# bf16 peak FLOPs/s per chip by TPU generation (public spec sheets) —
# shared by the telemetry plane and bench.py.  util/xprof.py keeps a
# jax-free mirror of these tables (importing this module executes the
# train package __init__, which drags jax); tests/test_xprof.py pins
# the two against each other.
PEAK_FLOPS_BY_GEN: Dict[str, float] = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

# HBM bandwidth per chip — the roofline's memory roof.
PEAK_HBM_BYTES_PER_SEC_BY_GEN: Dict[str, float] = {
    "v4": 1228e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v6e": 1638e9,
}


@dataclass
class TelemetryConfig:
    """Declared model-cost figures the telemetry plane needs to turn
    per-step reports into tokens/sec and achieved MFU gauges (the
    runtime cannot derive FLOPs-per-token from a closed jit).

    ``model_flops_per_token`` is the training cost (fwd+bwd) per token
    — e.g. ``GPT2Config.flops_per_token()``.  With it unset (0) the
    MFU gauge is simply not emitted; step-time and goodput metrics
    work regardless.
    """

    model_flops_per_token: float = 0.0
    tokens_per_step: float = 0.0       # per-worker tokens per report
    peak_flops_per_device: float = 0.0  # 0 = resolve from the TPU gen
    devices_per_worker: int = 1

    def resolved_peak_flops(self) -> float:
        if self.peak_flops_per_device > 0:
            return self.peak_flops_per_device
        env = os.environ.get("RT_PEAK_FLOPS_PER_DEVICE", "")
        if env:
            try:
                return float(env)
            except ValueError:
                pass
        gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
        return PEAK_FLOPS_BY_GEN.get(gen, PEAK_FLOPS_BY_GEN["v5e"])


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.name or "train_run"
        return os.path.join(base, name)


@dataclass
class Result:
    """What fit() returns (ref: python/ray/air/result.py)."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Any] = None
    path: str = ""
    error: Optional[BaseException] = None
    metrics_history: list = field(default_factory=list)
