"""Train-stack configuration dataclasses.

Role-equivalent to the reference's air config surface (ref:
python/ray/air/config.py ScalingConfig/RunConfig/FailureConfig/
CheckpointConfig, python/ray/train/_checkpoint.py).  TPU-era default: a
worker is one TPU *host* (use_tpu implies chips-per-worker resources and
STRICT_SPREAD gang placement so worker == jax process).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = float(os.environ.get("RT_TPU_PER_WORKER", 4))
        return res


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.name or "train_run"
        return os.path.join(base, name)


@dataclass
class Result:
    """What fit() returns (ref: python/ray/air/result.py)."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Any] = None
    path: str = ""
    error: Optional[BaseException] = None
    metrics_history: list = field(default_factory=list)
