"""Multi-host training plane: gang meshes, sharded state, global batches.

The integration layer that takes `JaxTrainer` from single-process to a
gang-scheduled multi-process mesh (ISSUE 15 / ROADMAP "training half"):

**Gang bootstrap.**  ``setup_distributed_mesh`` runs inside each rank's
train loop: rank 0 is the coordinator (its address rendezvouses through
the controller KV via the collective library's XLA group — the gang IS
an XLA collective group named ``train/<attempt_id>``), every rank joins
``jax.distributed``, and the global device view is laid out as one
``Mesh`` with ``fsdp``/``tensor`` axes derived from the gang topology
(CPU multi-process backend in CI, TPU ICI in production — same code).

**Process-contiguous layout invariant.**  Devices enter the mesh in
process-major order and the mesh is a C-order reshape, so rank r's
devices occupy a CONTIGUOUS block of flattened mesh coordinates.  That
single invariant is what makes three independent pieces of math agree:

- ``mesh_coords_for_rank`` here == the sharded checkpoint plane's
  ``coords_for_rank`` (host-mode saves split the same flattened mesh),
- ``global_batch_slice`` (the rows of the global batch a rank feeds)
  lines up with the fsdp rows its devices hold, and
- ``jax.make_array_from_process_local_data`` placement (contiguous
  sub-batch per process) reconstructs the intended global batch.

**Sharded state.**  ``shard_train_state`` drives the GPT-2/Llama
partition-rule sets (``models.*_partition_rules``) through
``match_partition_rules`` over the WHOLE TrainState — optimizer moments
mirror param paths, so one rule set places params and moments alike —
and materializes global jax Arrays under ``NamedSharding`` without any
host-side gather (``make_array_from_callback`` when multi-process).

**Elastic resume.**  Nothing here special-cases restore: the PR-10
sharded checkpoint plane saves the distributed TrainState per-rank
(jax arrays contribute ``addressable_shards``), and a restarted attempt
at ANY world size calls ``setup_distributed_mesh`` +
``session.load_sharded_checkpoint(mesh=..., target=...)`` — the
manifest's slice math reshards N→M.

Pure topology math lives at the top, jax-free at import time, so the
unit tests (and the doctor CLI) never pay a jax import.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# ===================================================================
# pure topology math (no jax — unit-testable, import-light)
# ===================================================================


def derive_mesh_shape(num_hosts: int, devices_per_host: int, *,
                      fsdp: Optional[int] = None,
                      tensor: Optional[int] = None
                      ) -> Dict[str, int]:
    """fsdp/tensor axis sizes from the gang topology.

    Default policy: the ``tensor`` axis stays INSIDE a host (ICI-
    adjacent on TPU — cross-host tensor parallelism pays DCN latency
    per matmul), so multi-host gangs get ``tensor=devices_per_host``
    and shard everything else over ``fsdp``; a single host defaults to
    pure FSDP over its local chips.  Either axis can be pinned
    explicitly; the other is derived; both pinned is validated.
    """
    if num_hosts < 1 or devices_per_host < 1:
        raise ValueError(
            f"invalid gang topology: {num_hosts} hosts x "
            f"{devices_per_host} devices")
    total = num_hosts * devices_per_host
    if fsdp is None and tensor is None:
        tensor = devices_per_host if num_hosts > 1 else 1
        fsdp = total // tensor
    elif fsdp is None:
        if tensor < 1 or total % tensor:
            raise ValueError(
                f"tensor={tensor} does not divide {total} devices")
        fsdp = total // tensor
    elif tensor is None:
        if fsdp < 1 or total % fsdp:
            raise ValueError(
                f"fsdp={fsdp} does not divide {total} devices")
        tensor = total // fsdp
    if fsdp * tensor != total:
        raise ValueError(
            f"mesh fsdp={fsdp} x tensor={tensor} needs "
            f"{fsdp * tensor} devices, gang has {total}")
    return {"fsdp": fsdp, "tensor": tensor}


def mesh_coords_for_rank(axis_sizes: Dict[str, int], rank: int,
                         world: int) -> List[Dict[str, int]]:
    """Mesh coordinates owned by rank ``rank`` of ``world`` under the
    process-contiguous layout: the C-order flattened mesh is split into
    ``world`` contiguous blocks (first axis slowest).

    MUST agree with ``sharded_checkpoint.coords_for_rank`` — a
    host-mode sharded save performed on these coordinates restores
    onto a gang mesh built here and vice versa (pinned by unit test).
    """
    if world < 1 or not 0 <= rank < world:
        raise ValueError(f"rank {rank} out of range for world {world}")
    names = list(axis_sizes)
    sizes = [int(axis_sizes[a]) for a in names]
    n = 1
    for s in sizes:
        if s < 1:
            raise ValueError(f"axis sizes must be >= 1, got "
                             f"{axis_sizes}")
        n *= s
    lo = rank * n // world
    hi = (rank + 1) * n // world
    out: List[Dict[str, int]] = []
    for lin in range(lo, hi):
        coord: Dict[str, int] = {}
        rem = lin
        for name, size in zip(reversed(names), reversed(sizes)):
            coord[name] = rem % size
            rem //= size
        out.append({a: coord[a] for a in names})
    return out


def global_batch_slice(global_batch_size: int,
                       mesh_shape: Dict[str, int], rank: int,
                       world: int) -> Tuple[int, int]:
    """[start, stop) rows of the global batch rank ``rank`` feeds when
    the batch dim is sharded along ``fsdp``.

    Derivation: under the process-contiguous layout rank r holds
    devices [r*D/world, (r+1)*D/world); device d sits on fsdp row
    ``d // tensor``; the rank must supply the rows of every fsdp row
    its devices touch.  When ``tensor`` spans processes, ranks sharing
    an fsdp row return IDENTICAL slices (they are replicas along the
    batch dim — `make_array_from_process_local_data` requires replica
    hosts to present identical data).
    """
    F = int(mesh_shape.get("fsdp", 1))
    T = int(mesh_shape.get("tensor", 1))
    D = F * T
    if world < 1 or not 0 <= rank < world:
        raise ValueError(f"rank {rank} out of range for world {world}")
    if D % world:
        raise ValueError(
            f"{D} mesh devices not divisible by world {world}")
    if global_batch_size % F:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by "
            f"fsdp={F}")
    per_rank_devs = D // world
    lo_dev = rank * per_rank_devs
    hi_dev = lo_dev + per_rank_devs
    f_lo = lo_dev // T
    f_hi = (hi_dev - 1) // T + 1
    per_row = global_batch_size // F
    return f_lo * per_row, f_hi * per_row


# ===================================================================
# model rule-set hookup
# ===================================================================

def rules_for_model(name: str):
    """The partition-rule set for a model family by name — the one
    registry the trainer/bench/CLI surfaces share (lazy imports: the
    registry itself never pays flax)."""
    from ..models import PARTITION_RULE_SETS

    key = name.lower().replace("-", "").replace("_", "")
    fn = PARTITION_RULE_SETS.get(key)
    if fn is None:
        raise KeyError(
            f"no partition-rule set for model {name!r}; known: "
            f"{sorted(PARTITION_RULE_SETS)}")
    return fn()


# ===================================================================
# jax layer — gang bootstrap, sharded placement, global batches
# ===================================================================

def mesh_axis_sizes(mesh) -> Dict[str, int]:
    """THE ordered {axis: size} mapping of a jax Mesh — ordered as the
    mesh's device array is laid out, which is the order replica-group
    device ids unravel to mesh coordinates (util/xprof.py's
    collective-to-axis attribution) and the order sharded checkpoints
    record as ``mesh_axes``.  One definition so the two planes cannot
    disagree."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))


@dataclass
class DistributedMesh:
    """The gang's resolved mesh plus the topology facts train loops
    need: rank/world for batch slicing, axis sizes for checkpoint
    ``mesh_axes``."""

    mesh: Any
    axis_sizes: Dict[str, int] = field(default_factory=dict)
    rank: int = 0
    world: int = 1
    group_name: str = ""

    def batch_sharding(self, spec: Any = None):
        """NamedSharding for batches: batch dim over ``fsdp`` unless a
        spec says otherwise (pruned to the mesh's real axes)."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as PS

        from ..parallel.partition_rules import prune_spec

        spec = PS("fsdp") if spec is None else spec
        return NamedSharding(self.mesh,
                             prune_spec(spec,
                                        mesh_axis_sizes(self.mesh)))

    def batch_slice(self, global_batch_size: int) -> Tuple[int, int]:
        """The rows of the global batch THIS rank feeds."""
        return global_batch_slice(global_batch_size, self.axis_sizes,
                                  self.rank, self.world)

    def coords(self) -> List[Dict[str, int]]:
        """This rank's mesh coordinates (== what a host-mode sharded
        save would assign it)."""
        return mesh_coords_for_rank(self.axis_sizes, self.rank,
                                    self.world)


def setup_distributed_mesh(*, fsdp: Optional[int] = None,
                           tensor: Optional[int] = None,
                           group_name: Optional[str] = None
                           ) -> DistributedMesh:
    """Gang bootstrap, called from INSIDE each rank's train loop.

    World > 1: joins (or creates) the gang's XLA collective group —
    rank 0 publishes the jax.distributed coordinator address through
    the controller KV, every rank rendezvouses (the entry-stamped
    ``distributed_init`` op `rt doctor` watches) — then lays the
    global device view out as a process-contiguous fsdp x tensor mesh.
    World 1 (including an elastic resume landed on one host) never
    touches jax.distributed and meshes over LOCAL devices only.
    """
    import jax

    from . import session as session_mod

    try:
        sess = session_mod.get_session()
        rank, world = sess.world_rank, sess.world_size
        attempt = sess.attempt_id
    except RuntimeError:
        rank, world, attempt = 0, 1, ""

    gname = group_name or (f"train/{attempt}" if attempt else "")
    if world > 1:
        from .. import collective as col

        if not gname:
            gname = "train/default"
        if not col.is_group_initialized(gname):
            col.init_collective_group(world, rank, backend="xla",
                                      group_name=gname)
        from ..parallel.mesh import process_contiguous_devices

        devices = process_contiguous_devices()
        if len(devices) % world:
            raise RuntimeError(
                f"{len(devices)} global devices not divisible by "
                f"world {world}")
        per_host = len(devices) // world
    else:
        # Local devices ONLY: a resumed world-1 attempt may run in a
        # process whose stale jax.distributed view still spans dead
        # peers; the global view must not leak into a 1-host mesh.
        devices = list(jax.local_devices())
        per_host = len(devices)
    shape = derive_mesh_shape(world, per_host, fsdp=fsdp,
                              tensor=tensor)
    mesh = gang_mesh(shape, devices)
    return DistributedMesh(mesh=mesh, axis_sizes=shape, rank=rank,
                           world=world, group_name=gname)


def gang_mesh(axis_sizes: Dict[str, int],
              devices: Optional[List[Any]] = None):
    """Process-contiguous mesh over the gang (see
    ``parallel.mesh.gang_mesh`` for the layout invariant)."""
    from ..parallel.mesh import gang_mesh as _gang_mesh

    return _gang_mesh(axis_sizes, devices)


def state_specs(state: Any, rules, *, default: Any = None) -> Any:
    """PartitionSpec tree over a WHOLE TrainState from a model's rule
    set: scalars (step, optax counts) replicate, optimizer moments
    match because their paths embed the param path (``re.search``)."""
    from ..parallel.partition_rules import match_partition_rules

    return match_partition_rules(rules, state, default=default)


def shard_host_tree(tree: Any, mesh, specs: Any) -> Any:
    """Host tree -> global jax Arrays under the specs' NamedShardings.

    Single-process: plain ``device_put``.  Multi-process: every rank
    holds the full host value (deterministic init), and
    ``make_array_from_callback`` hands each addressable device ONLY
    its slice — no gather, no cross-host transfer; HBM per host stays
    1/fsdp of the model."""
    import jax
    import numpy as np

    from ..parallel.partition_rules import tree_shardings

    shardings = tree_shardings(mesh, specs)
    multiprocess = jax.process_count() > 1

    def put(x, s):
        if not multiprocess:
            return jax.device_put(x, s)
        host = np.asarray(x)
        return jax.make_array_from_callback(
            host.shape, s, lambda idx: host[idx])

    return jax.tree_util.tree_map(put, tree, shardings)


def shard_train_state(state: Any, mesh, rules, *,
                      default: Any = None) -> Tuple[Any, Any]:
    """Rule-driven NamedSharding placement of a TrainState onto the
    gang mesh; returns ``(sharded_state, specs)`` — the specs double
    as the sharded checkpoint plane's per-leaf manifest specs."""
    specs = state_specs(state, rules, default=default)
    return shard_host_tree(state, mesh, specs), specs


def put_global_batch(local_batch: Any, mesh, *, spec: Any = None,
                     global_batch_size: Optional[int] = None) -> Any:
    """Per-rank batch slice -> ONE global array sharded along the data
    (``fsdp``) axis.  Single-process: device_put.  Multi-process:
    ``make_array_from_process_local_data`` — each host contributes
    only the rows it loaded (``global_batch_slice`` rows), the runtime
    wires them into the global batch with zero host-side gather."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as PS

    from ..parallel.partition_rules import prune_spec

    spec = PS("fsdp") if spec is None else spec
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    sharding = NamedSharding(mesh, prune_spec(spec, sizes))
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), local_batch)

    def put(x):
        x = np.asarray(x)
        gshape = None
        if global_batch_size is not None:
            gshape = (int(global_batch_size),) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x,
                                                      gshape)

    return jax.tree_util.tree_map(put, local_batch)


def batch_transfer(sharding, *,
                   global_batch_size: Optional[int] = None
                   ) -> Callable[[Any], Any]:
    """The ``transfer`` callable ``iter_device_batches(sharding=...)``
    builds: per-batch placement under a NamedSharding target, safe in
    both single- and multi-process worlds (no host-side gather — each
    process ships only its local rows)."""
    import jax

    def transfer(batch):
        if jax.process_count() == 1:
            # device_put maps one sharding over every leaf.
            return jax.device_put(batch, sharding)
        import numpy as np

        def put(x):
            x = np.asarray(x)
            gshape = None
            if global_batch_size is not None:
                gshape = (int(global_batch_size),) + x.shape[1:]
            return jax.make_array_from_process_local_data(
                sharding, x, gshape)

        return jax.tree_util.tree_map(put, batch)

    return transfer


def metrics_to_host(metrics: Dict[str, Any]) -> Dict[str, float]:
    """Fully-replicated step metrics -> python floats every rank can
    report (a multi-process global scalar supports float() only
    because it IS fully replicated)."""
    import numpy as np

    return {k: float(np.asarray(v)) for k, v in metrics.items()}
