"""Trainers: BaseTrainer / DataParallelTrainer / JaxTrainer / TorchTrainer.

Role-equivalent to the reference's trainer stack (ref:
train/base_trainer.py:111 BaseTrainer.fit, data_parallel_trainer.py:25,
backend_executor.py:69): fit() builds a WorkerGroup (gang-placed), runs
the backend bootstrap hook, initializes per-worker sessions, executes the
user's train_loop_per_worker, streams session.report payloads through a
result-queue actor, persists rank-0 checkpoints via CheckpointManager,
and on worker failure restarts the group from the latest checkpoint up to
FailureConfig.max_failures times.

JaxTrainer is the TPU flagship (BASELINE.json north star): backend =
jax.distributed over the gang; inside the loop workers build meshes over
the global device view (ray_tpu.parallel) for DP/FSDP/TP/SP.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

import ray_tpu
from .backend import Backend, JaxBackend, TorchBackend
from .checkpoint import Checkpoint, CheckpointManager
from .config import (CheckpointConfig, FailureConfig, Result, RunConfig,
                     ScalingConfig)
from .worker_group import WorkerGroup, WorkerGroupError


@ray_tpu.remote
class _ResultQueue:
    """Collects session.report payloads from all ranks; doubles as the
    gang's interruption flag (the drain notice travels driver ->
    queue -> every rank's session poll — the queue is the one actor
    every rank already talks to)."""

    def __init__(self):
        self.items = []
        self.interrupt = None

    def push(self, payload):
        self.items.append(payload)
        return len(self.items)

    def drain(self):
        out, self.items = self.items, []
        return out

    def set_interrupt(self, info):
        def _dl(n):
            return n.get("deadline") or float("inf")

        # Earliest DEADLINE wins, not first arrival: a later notice
        # with a tighter deadline (a real preemption landing during a
        # leisurely operator drain) must reach rank 0, or it races its
        # checkpoint against the wrong clock.
        if self.interrupt is None or _dl(info) < _dl(self.interrupt):
            self.interrupt = dict(info)
        return True

    def interrupt_info(self):
        return self.interrupt


class BaseTrainer:
    backend_cls = Backend

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        from ..core import serialization

        # The loop rides inside task args; make its module ship by value.
        serialization.ensure_code_portable(train_loop_per_worker)
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    # ------------------------------------------------------------------ fit
    def fit(self) -> Result:
        """v1 fit == the v2 controller with a fixed gang size; one
        retry/resume/checkpoint loop lives in v2.TrainControllerV2."""
        from .v2 import (FailurePolicy, FixedScalingPolicy,
                         TrainControllerV2)

        controller = TrainControllerV2(
            self,
            scaling_policy=FixedScalingPolicy(
                self.scaling_config.num_workers),
            failure_policy=FailurePolicy(
                self.run_config.failure_config.max_failures))
        return controller.fit()

    # -------------------------------------------------------------- attempt
    def _run_attempt(self, manager: CheckpointManager,
                     start_ckpt: Optional[Checkpoint],
                     history: list) -> Dict:
        run_id = uuid.uuid4().hex[:8]
        sc = self.scaling_config
        group = WorkerGroup(
            sc.num_workers,
            resources_per_worker=sc.worker_resources(),
            placement_strategy=sc.placement_strategy
            if sc.num_workers > 1 else None)
        # num_cpus=0: the queue is a metadata actor, and it must be
        # schedulable even when the gang's placement group reserves
        # every CPU in the cluster — a queue that cannot start
        # deadlocks the whole attempt (and carries the drain plane's
        # interruption flag, which must work under exactly that
        # full-reservation pressure).
        queue = _ResultQueue.options(
            name=f"train_results_{run_id}", num_cpus=0).remote()
        backend = self.backend_cls()
        try:
            if sc.worker_env:
                # Before the backend hook: jax reads XLA_FLAGS and
                # friends at first import, which happens inside
                # on_start's bootstrap.
                group.set_env(dict(sc.worker_env))
            backend.on_start(group, run_id)
            local_infos = group.local_ranks()
            # Shard datasets across ranks where supported.
            shard_specs: Dict[int, Dict[str, Any]] = {
                r: {} for r in range(sc.num_workers)}
            for name, ds in self.datasets.items():
                shards = self._shard_dataset(ds, sc.num_workers)
                for r in range(sc.num_workers):
                    shard_specs[r][name] = shards[r]
            refs = []
            for w, info in zip(group.workers, local_infos):
                refs.append(group.execute_async_single(
                    w, _worker_entry, self.train_loop,
                    self.train_loop_config, w.rank, sc.num_workers,
                    info, queue, start_ckpt.path if start_ckpt else None,
                    shard_specs[w.rank],
                    self.run_config.name or "train_run",
                    self.run_config.telemetry,
                    os.environ.get("RT_JOB_ID", ""),
                    self.run_config.resolved_storage_path(),
                    run_id))
            final_metrics: Dict = {}
            pending = list(refs)
            self._drain_notice = None
            self._drain_notices = {}
            self._last_drain_poll = 0.0
            while pending:
                done, pending = ray_tpu.wait(pending, num_returns=1,
                                             timeout=1.0)
                self._drain(queue, manager, history)
                self._poll_drain(group, queue)
                for ref in done:
                    try:
                        ray_tpu.get(ref)
                    except Exception as e:  # noqa: BLE001
                        rank = refs.index(ref)
                        # force=True: the loop's poll just ran and the
                        # throttle would hide a notice that landed in
                        # the last second — this path runs once per
                        # attempt, so the extra RPC is free.
                        self._poll_drain(group, queue, force=True)
                        notices = getattr(self, "_drain_notices", {})
                        notice = notices.get(
                            group.workers[rank].node_id)
                        if notice is None and notices:
                            # The failed rank sits on a HEALTHY node,
                            # but a gang peer's node is draining: the
                            # first observed failure of a preempted
                            # gang is often a surviving rank whose
                            # collective to the dying peer broke.
                            # Infra errors in that window are the
                            # cascade of the announced failure;
                            # deterministic user-code exceptions keep
                            # normal accounting (they would recur on
                            # any node).
                            from .worker_group import \
                                DETERMINISTIC_ERRORS

                            if not isinstance(e, DETERMINISTIC_ERRORS):
                                notice = min(
                                    notices.values(),
                                    key=lambda n:
                                    n.get("deadline") or float("inf"))
                        if notice is not None:
                            # ANNOUNCED failure: the failed rank's OWN
                            # node told us it was going before it
                            # died.  Classify so the controller
                            # restarts from the checkpoint-on-notice
                            # without burning a max_failures slot.  A
                            # rank failing on a HEALTHY node while
                            # some other node drains is still a crash
                            # (or a user bug) and keeps normal
                            # accounting.
                            from .worker_group import PreemptionError

                            raise WorkerGroupError(rank, PreemptionError(
                                f"worker {rank} lost to node drain/"
                                f"preemption "
                                f"({notice.get('reason', '?')})",
                                node_id=notice.get("node_id", ""),
                                reason=notice.get("reason", ""),
                                cause=e)) from e
                        raise WorkerGroupError(rank, e) from e
            self._drain(queue, manager, history)
            if history:
                final_metrics = history[-1]["metrics"]
            return final_metrics
        finally:
            self._push_driver_metrics(force=True)
            try:
                backend.on_shutdown(group)
            except Exception:
                pass
            group.shutdown()
            try:
                ray_tpu.kill(queue)
            except Exception:
                pass

    def _poll_drain(self, group: WorkerGroup, queue, force: bool = False):
        """Watch for drain/preemption notices on nodes hosting the
        gang (throttled to ~1 poll/s unless ``force``).  Notices
        accumulate in ``self._drain_notices`` keyed by node id (a
        preemption wave can drain several gang nodes at once); the
        first hit flags the run's result queue so every rank's session
        sees ``interrupted()`` and rank 0 can checkpoint-on-notice
        inside the grace window."""
        notices = getattr(self, "_drain_notices", None)
        if notices is None:
            notices = self._drain_notices = {}
        now = time.time()
        if not force and \
                now - getattr(self, "_last_drain_poll", 0.0) < 1.0:
            return self._drain_notice
        self._last_drain_poll = now
        try:
            from ..core import runtime as runtime_mod

            rt = runtime_mod.get_runtime_quiet()
            if rt is None or not hasattr(rt, "controller_call"):
                return None
            gang_nodes = {w.node_id for w in group.workers if w.node_id}
            for n in rt.controller_call("list_nodes", {}):
                nid = n["node_id"]
                nid = nid.hex() if hasattr(nid, "hex") else str(nid)
                if not n.get("draining") or nid not in gang_nodes \
                        or nid in notices:
                    continue
                self._register_notice(notices, nid, {
                    "node_id": nid,
                    "reason": n.get("drain_reason", ""),
                    "deadline": n.get("drain_deadline", 0.0)}, queue)
            # Job-level preemption notice (multi-tenant plane): a
            # higher-priority gang selected THIS job as a victim.  The
            # notice carries a remaining-seconds deadline (the node-
            # drain clock discipline) and drives the same interrupt
            # flag, so rank 0 checkpoint-on-notice works unchanged.
            job = os.environ.get("RT_JOB_ID", "")
            if job and f"job:{job}" not in notices:
                r = rt.controller_call("job_preemption_state",
                                       {"job_id": job})
                if r and r.get("preempting"):
                    self._register_notice(notices, f"job:{job}", {
                        "node_id": "",
                        "job": job,
                        "reason": r.get("reason")
                        or f"job {job} preempted",
                        "deadline": time.time()
                        + float(r.get("remaining_s") or 0.0)}, queue)
        except Exception:
            return self._drain_notice  # polling must never fail fit
        return self._drain_notice

    def _register_notice(self, notices, key, notice, queue) -> None:
        notices[key] = notice
        if self._drain_notice is None:
            self._drain_notice = notice
        # EVERY new notice reaches the queue — it keeps the one with
        # the earliest deadline, so a tighter notice arriving later
        # still reaches the workers.
        try:
            ray_tpu.get(queue.set_interrupt.remote(notice))
        except Exception:
            pass  # queue gone == gang already dying
        from ..util import flight_recorder

        flight_recorder.record("train_drain_notice", **notice)

    def _drain(self, queue, manager: CheckpointManager,
               history: list) -> None:
        for payload in ray_tpu.get(queue.drain.remote()):
            if payload.get("checkpoint_path") and payload["rank"] == 0:
                ckpt = manager.register(payload["checkpoint_path"],
                                        payload["metrics"])
                payload["checkpoint_path"] = ckpt.path
            if payload["rank"] == 0:
                history.append(payload)
        self._push_driver_metrics()

    def _push_driver_metrics(self, force: bool = False) -> None:
        """Driver-side telemetry (goodput ledger, worker-group and
        checkpoint metrics) has no heartbeat of its own — ship the
        local registry to the controller on the drain cadence,
        throttled to the metrics report period."""
        now = time.time()
        last = getattr(self, "_last_metrics_push", 0.0)
        period = 2.0
        try:
            from ..core import runtime as runtime_mod

            rt = runtime_mod.get_runtime_quiet()
            if rt is None or not hasattr(rt, "controller_call"):
                return
            period = min(
                2.0, getattr(rt.config, "metrics_report_period_s", 2.0))
            if not force and now - last < period:
                return
            self._last_metrics_push = now
            from ..util import spans
            from ..util.metrics import registry

            snap = registry().snapshot()
            if snap:
                rt.controller_call("report_metrics", {
                    "source": f"driver-{os.getpid()}",
                    "snapshot": snap})
            # Driver-side spans (goodput phases, start_span blocks)
            # ride the same cadence into the controller span sink.
            spans.flush(source=f"driver-{os.getpid()}")
        except Exception:
            pass  # telemetry must never fail the fit loop

    @staticmethod
    def _shard_dataset(ds, num_shards: int):
        if hasattr(ds, "split"):
            return ds.split(num_shards)
        if hasattr(ds, "shard"):
            return [ds.shard(num_shards, i) for i in range(num_shards)]
        return [ds] * num_shards  # replicated (caller shards by rank)


def _worker_entry(train_loop, config, rank, world, local_info, queue,
                  ckpt_path, shards, experiment_name, telemetry=None,
                  job_id="", storage_dir="", run_id=""):
    """Runs inside the worker actor: set up the session, run user code."""
    from . import session as session_mod
    from .checkpoint import Checkpoint

    if job_id:
        # Per-job goodput attribution: the worker process was spawned
        # by the node agent (not the job's entrypoint), so the
        # submitted-job identity travels with the gang, not the env.
        from ..util import goodput as goodput_mod

        goodput_mod.set_job_id(job_id)
    session_mod.init_session(
        world_rank=rank, world_size=world,
        local_rank=local_info["local_rank"],
        local_world_size=local_info["local_world_size"],
        node_rank=local_info["node_rank"],
        experiment_name=experiment_name,
        result_queue=queue,
        checkpoint=Checkpoint(ckpt_path) if ckpt_path else None,
        dataset_shards=shards,
        storage_dir=storage_dir,
        telemetry=telemetry,
        # The attempt's run_id doubles as the sharded-save commit
        # nonce: identical across ranks, fresh on every restart.
        attempt_id=run_id)
    from ..util import flight_recorder

    flight_recorder.record("train_worker_start", rank=rank,
                           world=world, experiment=experiment_name)
    try:
        return train_loop(config)
    finally:
        flight_recorder.record("train_worker_done", rank=rank)
        session_mod.shutdown_session()


class DataParallelTrainer(BaseTrainer):
    backend_cls = Backend


class JaxTrainer(DataParallelTrainer):
    """The TPU-native trainer (north star: ref BASELINE.json — a
    JaxTrainer in the Train stack with jax.distributed across the worker
    group and GSPMD meshes inside the loop)."""

    backend_cls = JaxBackend


class TorchTrainer(DataParallelTrainer):
    backend_cls = TorchBackend
