"""Pure training-step construction: optimizer, TrainState, sharded jit.

TPU-first: one compiled XLA program per step — loss, grads (via
jax.value_and_grad through remat'd blocks), optax update, all under a
single jit with donated state so HBM holds one copy of params+moments.
Parallelism arrives via the mesh shardings placed on the state by
``shard_state`` (DP grads become psums XLA inserts from the shardings —
no hand-written collectives here).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params, optimizer) -> "TrainState":
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=optimizer.init(params))


def make_optimizer(learning_rate: float = 3e-4,
                   warmup_steps: int = 100,
                   total_steps: int = 10000,
                   weight_decay: float = 0.1,
                   grad_clip: float = 1.0,
                   b1: float = 0.9, b2: float = 0.95) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=learning_rate,
        warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=learning_rate * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def make_train_step(loss_fn: Callable, optimizer
                    ) -> Callable[[TrainState, Dict], Tuple[TrainState, Dict]]:
    """loss_fn(params, batch) -> scalar.  Returns step(state, batch)."""

    def step(state: TrainState, batch) -> Tuple[TrainState, Dict]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss,
                   "grad_norm": optax.global_norm(grads)}
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state), metrics

    return step


def shard_state(state: TrainState, mesh, param_axes_fn, rules=None
                ) -> TrainState:
    """Place params AND optimizer moments with the param sharding rules
    (moments mirror param shapes, so the same logical axes apply)."""
    from ..parallel.sharding import shard_pytree

    params = shard_pytree(state.params, mesh, param_axes_fn, rules)

    def opt_axes(path: str, leaf):
        # Moment tensors repeat the param path inside the optax tree.
        return param_axes_fn(path, leaf)

    opt_state = jax.tree_util.tree_map(
        lambda x: x, state.opt_state)  # structural copy
    opt_state = shard_pytree(opt_state, mesh, opt_axes, rules)
    return TrainState(step=state.step, params=params, opt_state=opt_state)


def make_sharded_train_step(loss_fn, optimizer, mesh=None,
                            donate: bool = True, telemetry: bool = True,
                            state_shardings=None,
                            batch_sharding=None):
    """Jit the step; with a mesh, shardings propagate from the state
    placement (GSPMD), so no explicit in_shardings are needed.

    The multi-process path (train.distributed) passes the rule-derived
    ``state_shardings`` (and optionally a ``batch_sharding``)
    explicitly: jit then PINS the input/output state layout instead of
    inferring it, so the donated input buffer and the returned state
    provably share a layout (no resharding copy per step) and the step
    metrics come back fully replicated — the form every rank can read
    with ``float()`` and feed the goodput/MFU telemetry below.

    With ``telemetry`` (default), each call is timed host-side and
    attributed to the goodput ledger: the first invocation (trace +
    XLA compile) lands in the ``compile`` phase and sets the
    ``rt_train_compile_seconds`` gauge; later invocations land in
    ``compute`` and feed the dispatch-time histogram.  Host-side
    timing under async dispatch is an approximation — the per-step
    truth is the report-cadence ``rt_train_step_time_seconds``.
    """
    step = make_train_step(loss_fn, optimizer)
    jit_kwargs: Dict[str, Any] = {}
    if state_shardings is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        if batch_sharding is not None:
            jit_kwargs["in_shardings"] = (state_shardings,
                                          batch_sharding)
        out_mesh = mesh
        if out_mesh is None:
            leaves = jax.tree_util.tree_leaves(state_shardings)
            out_mesh = leaves[0].mesh if leaves else None
        if out_mesh is not None:
            # One replicated sharding is a tree prefix for the whole
            # metrics dict.
            jit_kwargs["out_shardings"] = (
                state_shardings,
                NamedSharding(out_mesh, PartitionSpec()))
    jitted = jax.jit(step, donate_argnums=(0,) if donate else (),
                     **jit_kwargs)
    if not telemetry:
        return jitted

    import time as _time

    from ..util import goodput

    mesh_axes = None
    if mesh is not None:
        try:
            from .distributed import mesh_axis_sizes

            mesh_axes = mesh_axis_sizes(mesh)
        except Exception:
            pass

    # aot[0]: None = first call pending, False = fell back to the
    # shape-polymorphic jit path, else the AOT-compiled executable
    # (the execution path from call one — compiling via
    # ``lower().compile()`` instead of jit's implicit cache lets the
    # xprof plane harvest cost/memory/collective facts without paying
    # a second compile).
    aot = [None]

    def timed_step(state, batch):
        first = aot[0] is None
        phase = "compile" if first else "compute"
        t0 = _time.perf_counter()
        with goodput.ledger().phase(phase):
            if first:
                try:
                    aot[0] = jitted.lower(state, batch).compile()
                except Exception:
                    aot[0] = False
            exe = aot[0] if aot[0] else jitted
            try:
                out = exe(state, batch)
            except Exception:
                if exe is jitted:
                    raise
                # New input shapes/shardings vs the AOT executable:
                # fall back to the polymorphic jit path for good and
                # count the recompile.
                aot[0] = False
                rt0 = _time.perf_counter()
                out = jitted(state, batch)
                try:
                    from ..util import xprof

                    xprof.count_compile(
                        "train_step",
                        _time.perf_counter() - rt0)
                except Exception:
                    pass
        dt = _time.perf_counter() - t0
        try:
            from ..util.metrics import Gauge, Histogram

            if first:
                Gauge("rt_train_compile_seconds",
                      "Host-side duration of the first (tracing + "
                      "XLA compile) step invocation.").set(dt)
                if aot[0]:
                    from ..util import xprof

                    xprof.register_compiled("train_step", aot[0],
                                            mesh_axes=mesh_axes,
                                            compile_seconds=dt)
            else:
                Histogram("rt_train_step_dispatch_seconds",
                          "Host-side duration of the jitted step call "
                          "(approximate under async dispatch)."
                          ).observe(dt)
        except Exception:
            pass
        return out

    return timed_step
