"""Per-worker training session: report/get_checkpoint/rank context.

Role-equivalent to the reference's _TrainSession (ref:
train/_internal/session.py:112, report at :672, get_checkpoint :772,
get_dataset_shard :1098).  The session is process-global inside each
training worker; ``report`` ships metrics (+ an optional checkpoint
directory) to the trainer through the result-queue actor, with rank 0
owning checkpoint persistence.
"""

from __future__ import annotations

import os
import tempfile
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from .checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


@dataclass
class TrainSession:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    result_queue: Any = None          # ActorHandle of _ResultQueue
    checkpoint: Optional[Checkpoint] = None
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    storage_dir: str = ""
    _report_index: int = 0

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self._report_index += 1
        payload = {"rank": self.world_rank, "metrics": dict(metrics),
                   "index": self._report_index,
                   "checkpoint_path": checkpoint.path if checkpoint
                   else None}
        if self.result_queue is not None:
            import ray_tpu

            ray_tpu.get(self.result_queue.push.remote(payload))

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard {name!r} was provided to "
                           f"the trainer")
        return shard


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError("Not inside a training worker session")
    return _session


def shutdown_session() -> None:
    global _session
    _session = None


# -- public functional API (ray.train.report style) -----------------------

def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_world_rank() -> int:
    return get_session().world_rank


def get_world_size() -> int:
    return get_session().world_size


def get_local_rank() -> int:
    return get_session().local_rank


@contextmanager
def checkpoint_dir():
    """Scratch dir for building a checkpoint before report()."""
    d = tempfile.mkdtemp(prefix="rt_ckpt_build_")
    yield d
