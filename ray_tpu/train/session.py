"""Per-worker training session: report/get_checkpoint/rank context.

Role-equivalent to the reference's _TrainSession (ref:
train/_internal/session.py:112, report at :672, get_checkpoint :772,
get_dataset_shard :1098).  The session is process-global inside each
training worker; ``report`` ships metrics (+ an optional checkpoint
directory) to the trainer through the result-queue actor, with rank 0
owning checkpoint persistence.
"""

from __future__ import annotations

import os
import tempfile
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from .checkpoint import Checkpoint
from .config import TelemetryConfig

_session: Optional["TrainSession"] = None


@dataclass
class TrainSession:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    result_queue: Any = None          # ActorHandle of _ResultQueue
    checkpoint: Optional[Checkpoint] = None
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    storage_dir: str = ""
    telemetry: Optional[TelemetryConfig] = None
    # Driver-issued per-attempt id, identical across the gang's ranks
    # and fresh on every (re)start — the sharded-save commit nonce
    # (save_id = "<step>:<attempt_id>"), so a re-save of a step whose
    # previous attempt was SIGKILLed mid-save can never commit that
    # attempt's stale shard indexes.
    attempt_id: str = ""
    _report_index: int = 0
    _last_report_ts: Optional[float] = None
    _clock: Any = time.monotonic  # injectable for telemetry tests
    # Drain plane: sticky interruption notice (a preemption/drain was
    # announced for a node hosting this gang).  Set by the throttled
    # result-queue poll; once set it never clears for this attempt.
    _interrupt: Optional[Dict[str, Any]] = None
    _last_interrupt_poll: float = 0.0
    _interrupt_poll_period_s: float = 1.0

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self._report_index += 1
        self._observe_step(metrics)
        payload = {"rank": self.world_rank, "metrics": dict(metrics),
                   "index": self._report_index,
                   "checkpoint_path": checkpoint.path if checkpoint
                   else None}
        if checkpoint is not None and self.interrupted():
            # Tag the payload so the driver (and the metrics history)
            # can tell a checkpoint-on-notice from a periodic save.
            payload["preempt_ckpt"] = True
        if self.result_queue is not None:
            import ray_tpu

            ray_tpu.get(self.result_queue.push.remote(payload))

    # ------------------------------------------------- drain/preemption
    def interruption(self) -> Optional[Dict[str, Any]]:
        """The drain notice for this gang, or None.  When a node
        hosting the gang enters DRAINING (preemption notice or ``rt
        drain``), the trainer driver flags the run's result queue; the
        session polls that flag (throttled to one RPC per
        ``_interrupt_poll_period_s``) so a per-step check costs ~0.

        The returned dict carries ``reason``, ``node_id`` and
        ``deadline`` (unix time the node is expected to die) — the
        budget rank 0 has for a checkpoint-on-notice.  Polling
        continues after the first notice: the queue keeps the
        earliest-deadline notice, and a tighter one arriving later
        (a real preemption during a leisurely operator drain) must
        replace the stale budget."""
        if self.result_queue is None:
            return self._interrupt
        now = self._clock()
        if now - self._last_interrupt_poll < \
                self._interrupt_poll_period_s:
            return self._interrupt
        self._last_interrupt_poll = now
        try:
            import ray_tpu

            latest = ray_tpu.get(
                self.result_queue.interrupt_info.remote())
            if latest is not None:
                self._interrupt = latest
        except Exception:
            pass  # queue dying usually means the gang is too
        return self._interrupt

    def interrupted(self) -> bool:
        """True once a drain/preemption notice covers this gang — the
        train loop should checkpoint (rank 0) and keep going; the
        controller restarts from that checkpoint without burning a
        ``max_failures`` slot."""
        return self.interruption() is not None

    def _observe_step(self, metrics: Dict[str, Any]) -> None:
        """Per-step telemetry: the report cadence IS the step cadence,
        so the delta between reports is the end-to-end step time (incl.
        data wait + host overhead); tokens/sec and achieved MFU derive
        from the declared TelemetryConfig figures."""
        try:
            from ..util.metrics import Gauge, Histogram

            now = self._clock()
            last, self._last_report_ts = self._last_report_ts, now
            step = metrics.get("step", self._report_index)
            Gauge("rt_train_step",
                  "Latest reported training step.").set(float(step))
            if last is None:
                return
            dt = max(now - last, 1e-9)
            Histogram("rt_train_step_time_seconds",
                      "Wall-clock between session.report calls "
                      "(per-step time).").observe(dt)
            # Timeline span per step, tagged step/rank: the cluster
            # timeline's per-rank step rows and the `rt timeline
            # --summary` critical path (slowest rank per step) are
            # built from these.
            try:
                from ..util import spans

                wall_end = time.time()
                spans.record_span(
                    "step", wall_end - dt, wall_end, cat="train_step",
                    tags={"step": int(float(step)),
                          "rank": self.world_rank})
            except Exception:
                pass
            tel = self.telemetry or TelemetryConfig()
            tokens = float(metrics.get("tokens",
                                       tel.tokens_per_step or 0.0))
            if tokens <= 0:
                return
            tps = tokens / dt
            Gauge("rt_train_tokens_per_sec",
                  "Per-worker training throughput.").set(tps)
            if tel.model_flops_per_token > 0:
                peak = tel.resolved_peak_flops() * max(
                    tel.devices_per_worker, 1)
                Gauge("rt_train_mfu",
                      "Achieved model FLOPs utilization (0-1) from "
                      "the declared FLOPs-per-token figure.").set(
                    tps * tel.model_flops_per_token / peak)
                # The roofline's measured point: achieved model
                # FLOP/s per worker (rt perf plots it against the
                # attainable ceiling at the program's intensity).
                Gauge("rt_train_achieved_flops_per_sec",
                      "Achieved model FLOP/s per worker from the "
                      "declared FLOPs-per-token figure.").set(
                    tps * tel.model_flops_per_token)
        except Exception:
            pass  # telemetry must never fail a training step

    def iter_device_batches(self, batches, *, depth: int = 2,
                            transfer=None, sharding=None,
                            global_batch_size=None):
        """Device-prefetching wrapper for this worker's step loop; see
        the module-level ``iter_device_batches``."""
        return iter_device_batches(batches, depth=depth,
                                   transfer=transfer,
                                   sharding=sharding,
                                   global_batch_size=global_batch_size)

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint

    # ------------------------------------------- sharded checkpointing
    def save_sharded_checkpoint(self, tree: Any, *, step: int,
                                specs: Any = None,
                                mesh_axes: Optional[Dict[str, int]]
                                = None,
                                meta: Optional[Dict] = None,
                                metrics: Optional[Dict] = None,
                                report: bool = True,
                                wait_timeout_s: float = 120.0
                                ) -> Dict[str, Any]:
        """Collective sharded save into the run directory: EVERY rank
        calls this with the same ``step``; each writes only its local
        shards (jax arrays contribute their device shards, host trees
        the slices of this rank's mesh coordinates per
        ``specs``/``mesh_axes``), rank 0 writes the manifest last,
        commits atomically, and — with ``report`` — ships the
        committed checkpoint through ``session.report`` so the
        driver's CheckpointManager adopts it in place (no copy).
        Restore side: ``load_sharded_checkpoint`` reshards onto
        whatever world/mesh the elastic restart landed on."""
        if not self.storage_dir:
            raise RuntimeError(
                "sharded checkpointing needs the run storage dir; "
                "this session was initialized without one")
        from .sharded_checkpoint import save_sharded

        path = os.path.join(self.storage_dir,
                            f"checkpoint_{int(step):06d}")
        m = dict(meta or {})
        m.setdefault("step", int(step))
        m.setdefault("world_size", self.world_size)
        result = save_sharded(
            path, tree, specs=specs, mesh_axes=mesh_axes,
            process_index=self.world_rank,
            process_count=self.world_size, meta=m,
            wait_timeout_s=wait_timeout_s,
            # Per-attempt commit nonce: every rank of this attempt
            # derives the same value, and a restarted attempt gets a
            # fresh one — rank 0 refuses a dead attempt's indexes.
            save_id=(f"{int(step)}:{self.attempt_id}"
                     if self.attempt_id else None))
        if result["committed"] and report:
            self.report({"step": int(step), **(metrics or {})},
                        checkpoint=Checkpoint(path))
        return result

    def load_sharded_checkpoint(self, *, mesh=None, specs: Any = None,
                                target: Any = None,
                                validate: bool = True
                                ) -> Optional[Any]:
        """Restore the attempt's resume checkpoint (if it is in the
        sharded format), resharded onto ``mesh`` — the world-M half of
        an elastic N→M restart.  Returns None when there is no
        checkpoint; raises if the checkpoint exists but is a blob
        (use ``get_checkpoint().load_pytree`` for those)."""
        ckpt = self.get_checkpoint()
        if ckpt is None:
            return None
        if not ckpt.is_sharded:
            raise ValueError(
                f"{ckpt.path} is not a sharded checkpoint; load it "
                f"with Checkpoint.load_pytree/load_json")
        return ckpt.load_sharded(mesh=mesh, specs=specs,
                                 target=target, validate=validate)

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard {name!r} was provided to "
                           f"the trainer")
        return shard


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError("Not inside a training worker session")
    return _session


def shutdown_session() -> None:
    global _session
    _session = None


# -- public functional API (ray.train.report style) -----------------------

def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def save_sharded_checkpoint(tree, *, step: int, specs=None,
                            mesh_axes=None, meta=None, metrics=None,
                            report: bool = True,
                            wait_timeout_s: float = 120.0):
    """Collective per-rank sharded save (see
    ``TrainSession.save_sharded_checkpoint``)."""
    return get_session().save_sharded_checkpoint(
        tree, step=step, specs=specs, mesh_axes=mesh_axes, meta=meta,
        metrics=metrics, report=report, wait_timeout_s=wait_timeout_s)


def load_sharded_checkpoint(*, mesh=None, specs=None, target=None,
                            validate: bool = True):
    """Reshard-on-restore of the attempt's resume checkpoint (see
    ``TrainSession.load_sharded_checkpoint``)."""
    return get_session().load_sharded_checkpoint(
        mesh=mesh, specs=specs, target=target, validate=validate)


def get_world_rank() -> int:
    return get_session().world_rank


def get_world_size() -> int:
    return get_session().world_size


def get_local_rank() -> int:
    return get_session().local_rank


def interrupted() -> bool:
    """True once a drain/preemption notice covers this gang."""
    return get_session().interrupted()


def interruption() -> Optional[Dict[str, Any]]:
    """The gang's drain notice ({reason, node_id, deadline}) or None."""
    return get_session().interruption()


@contextmanager
def checkpoint_dir():
    """Scratch dir for building a checkpoint before report()."""
    d = tempfile.mkdtemp(prefix="rt_ckpt_build_")
    yield d


@contextmanager
def checkpoint_on_notice():
    """Wrap the urgent save a train loop performs after
    ``interrupted()`` turns true: attributes the elapsed time to the
    ``checkpoint_on_notice`` goodput sub-phase (distinct from periodic
    ``checkpoint`` saves) and observes its duration histogram — the
    measured cost of converting an announced failure into a bounded
    one."""
    from ..util import goodput

    with goodput.timed_phase(
            "checkpoint_on_notice",
            "rt_train_ckpt_on_notice_seconds",
            "Rank-0 checkpoint save raced against a drain deadline."):
        yield


@contextmanager
def data_wait():
    """Wrap the blocking part of fetching the next batch: attributes
    the elapsed time to the ``data_stall`` goodput phase and observes
    the per-step data-wait histogram."""
    from ..util import goodput

    with goodput.timed_phase(
            "data_stall", "rt_train_data_wait_seconds",
            "Time the step loop spent waiting on input data."):
        yield


def iter_device_batches(batches, *, depth: int = 2, transfer=None,
                        sharding=None, global_batch_size=None):
    """Overlap host->device transfer with compute: a feeder thread runs
    ``jax.device_put`` on batch N+1 (N+2, ... up to ``depth``) while
    the step loop computes on batch N, so the loop dequeues
    already-transferring device arrays instead of paying batch
    assembly + H2D latency inside the step (the device-side half of
    the zero-stall ingest chain; ref: tf.data-style prefetch-to-device
    / the reference's iter_torch_batches device prefetch).

    Any residual dequeue wait — the pipeline genuinely starving — is
    charged to the ``data_stall`` goodput phase and the
    ``rt_train_data_wait_seconds`` histogram, so the goodput summary
    shows exactly how far from zero-stall the input pipeline runs.

    ``sharding`` targets a ``NamedSharding``: each prefetched batch
    lands as a global array sharded along the mesh's data axis with NO
    host-side gather — in a multi-process world each rank contributes
    only the rows it loaded (pass ``global_batch_size`` when the
    global row count cannot be inferred, e.g. batch replicated over
    some processes).  ``transfer`` overrides placement entirely (e.g.
    ``lambda b: jax.device_put(b, sharding)``); the default is a plain
    ``jax.device_put`` onto the worker's default device.  Works with
    any iterable of pytrees (dict-of-ndarray batches included).
    Abandoning the iterator mid-stream stops and joins the feeder
    (shared lifecycle with the block prefetcher: util.prefetch).
    """
    from ..util.prefetch import iter_prefetched

    if transfer is None and sharding is not None:
        from .distributed import batch_transfer

        transfer = batch_transfer(sharding,
                                  global_batch_size=global_batch_size)
    if transfer is None:
        import jax

        def transfer(b):
            # device_put is async-dispatch: enqueue the transfer in the
            # feeder, let the consumer's compute overlap it.
            return jax.device_put(b)

    return iter_prefetched(batches, depth=depth, transform=transfer,
                           wait_cm=data_wait,
                           thread_name="rt-device-prefetch")
