"""WorkerGroup — a gang of training-worker actors.

Role-equivalent to the reference's train worker group (ref:
train/_internal/worker_group.py): N actors created with per-worker
resources (optionally inside a STRICT_SPREAD placement group so each
worker is its own TPU host), ``execute`` fan-out of functions, and death
detection surfaced as WorkerGroupError.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ..util import PlacementGroupSchedulingStrategy, placement_group, \
    remove_placement_group


class WorkerGroupError(RuntimeError):
    def __init__(self, rank: int, cause: BaseException):
        self.rank = rank
        self.cause = cause
        super().__init__(f"training worker {rank} failed: {cause!r}")


# Exception types that recur on every attempt when raised by USER code
# inside the train loop: retrying burns the whole max_failures budget
# (and the TPU-hours behind it) on an error a stack trace already
# explains.  Infra errors never subclass these directly — a remote
# user exception re-raises as a TaskError dual-subclass
# (errors.make_task_error), so isinstance() still identifies them.
DETERMINISTIC_ERRORS = (
    ValueError, TypeError, KeyError, IndexError, AttributeError,
    ZeroDivisionError, AssertionError, NotImplementedError,
)


class PreemptionError(RuntimeError):
    """A training worker was lost to an ANNOUNCED failure: its node
    delivered a preemption/drain notice before dying.  The v2
    controller treats this differently from a crash — the restart does
    not consume a ``FailureConfig.max_failures`` budget slot, because
    preemption frequency is a property of the fleet, not of the job
    (cf. Bamboo NSDI'23 / Gemini SOSP'23 on spot-instance training)."""

    def __init__(self, message: str, node_id: str = "",
                 reason: str = "", cause: BaseException = None):
        super().__init__(message)
        self.node_id = node_id
        self.reason = reason
        self.cause = cause


@ray_tpu.remote
class _TrainWorkerActor:
    """Hosts the user's train loop; one per rank."""

    def __init__(self, rank: int):
        self.rank = rank
        self.env: Dict[str, str] = {}

    def set_env(self, env: Dict[str, str]):
        self.env.update(env)
        os.environ.update(env)
        return True

    def node_id(self) -> str:
        return os.environ.get("RT_NODE_ID", "")

    def run(self, fn_payload: bytes, args: tuple, kwargs: dict):
        import cloudpickle

        fn = cloudpickle.loads(fn_payload)
        return fn(*args, **kwargs)


@dataclass
class WorkerMeta:
    rank: int
    actor: Any
    node_id: str = ""


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_strategy: Optional[str] = None,
                 name_prefix: str = "train"):
        t_start = time.monotonic()
        self.num_workers = num_workers
        self._pg = None
        res = dict(resources_per_worker or {"CPU": 1.0})
        opts: Dict[str, Any] = {
            "num_cpus": res.pop("CPU", 1.0),
            "num_tpus": res.pop("TPU", None),
            "resources": res or None,
            "max_concurrency": 2,  # run() + control calls
        }
        if placement_strategy:
            bundles = []
            for _ in range(num_workers):
                b = {"CPU": opts["num_cpus"]}
                if opts["num_tpus"]:
                    b["TPU"] = opts["num_tpus"]
                if res:
                    b.update(res)
                bundles.append(b)
            self._pg = placement_group(bundles,
                                       strategy=placement_strategy)
            if not self._pg.wait(120):
                remove_placement_group(self._pg)
                raise TimeoutError(
                    f"placement group for {num_workers} training workers "
                    f"({bundles[0]}) not schedulable")
        self.workers: List[WorkerMeta] = []
        for rank in range(num_workers):
            o = dict(opts)
            if self._pg is not None:
                o["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    self._pg, rank)
            actor = _TrainWorkerActor.options(**o).remote(rank)
            self.workers.append(WorkerMeta(rank, actor))
        # Resolve node placement for local-rank computation.
        node_ids = ray_tpu.get([w.actor.node_id.remote()
                                for w in self.workers])
        for w, nid in zip(self.workers, node_ids):
            w.node_id = nid
        try:
            from ..util.metrics import Gauge, Histogram

            Histogram("rt_train_worker_group_start_seconds",
                      "Gang placement + actor spawn time for a "
                      "training worker group.").observe(
                time.monotonic() - t_start)
            Gauge("rt_train_workers",
                  "Workers in the most recent training gang.").set(
                float(num_workers))
        except Exception:
            pass

    def local_ranks(self) -> List[Dict[str, int]]:
        """Per-worker local rank/size/node-rank from node placement."""
        by_node: Dict[str, List[int]] = {}
        for w in self.workers:
            by_node.setdefault(w.node_id, []).append(w.rank)
        node_order = sorted(by_node)
        out = []
        for w in self.workers:
            ranks = sorted(by_node[w.node_id])
            out.append({
                "local_rank": ranks.index(w.rank),
                "local_world_size": len(ranks),
                "node_rank": node_order.index(w.node_id),
            })
        return out

    def set_env(self, env: Dict[str, str]) -> None:
        ray_tpu.get([w.actor.set_env.remote(env) for w in self.workers])

    def execute_async(self, fn: Callable, *args, **kwargs) -> List[Any]:
        from ..core import serialization

        payload = serialization.dumps_code(fn)
        return [w.actor.run.remote(payload, args, kwargs)
                for w in self.workers]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_async_single(self, worker: "WorkerMeta", fn: Callable,
                             *args, **kwargs):
        from ..core import serialization

        payload = serialization.dumps_code(fn)
        return worker.actor.run.remote(payload, args, kwargs)

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(self.execute_async_single(
            self.workers[rank], fn, *args, **kwargs))

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        self.workers.clear()
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
