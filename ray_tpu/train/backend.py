"""Training backends — per-framework worker-group bootstrap hooks.

Role-equivalent to the reference's Backend classes (ref:
train/_internal/backend_executor.py + train/torch/config.py TCP-store
rendezvous, train/tensorflow/config.py TF_CONFIG).  The TPU-native
flagship is JaxBackend: worker 0 publishes a coordinator address through
the controller KV (the named-rendezvous pattern) and every worker calls
jax.distributed.initialize, after which the global device view spans the
gang and meshes from ray_tpu.parallel cover every chip.
"""

from __future__ import annotations

from typing import Dict, List

import ray_tpu


class Backend:
    """Subclass per framework; hooks run at group start/shutdown."""

    def on_start(self, worker_group, run_id: str) -> None:
        pass

    def on_shutdown(self, worker_group) -> None:
        pass


class JaxBackend(Backend):
    def on_start(self, worker_group, run_id: str) -> None:
        num = worker_group.num_workers
        if num == 1:
            return  # single-process jax needs no distributed init

        def _bootstrap(rank: int, world: int, group_name: str):
            # The gang IS an XLA collective group: jax.distributed
            # bootstrap (coordinator rendezvous through the controller
            # KV) lives in one place — the collective library — and
            # training code can later grab the group's global_mesh()
            # or build a gang mesh via train.distributed.
            from ray_tpu import collective as col

            if col.is_group_initialized(group_name):
                g = col.get_group(group_name)
            else:
                g = col.init_collective_group(world, rank,
                                              backend="xla",
                                              group_name=group_name)
            import jax

            return {"devices": len(g.devices),
                    "local_devices": jax.local_device_count(),
                    "process_count": jax.process_count()}

        group_name = f"train/{run_id}"
        refs = []
        for w in worker_group.workers:
            from ..core import serialization

            payload = serialization.dumps_code(_bootstrap)
            refs.append(w.actor.run.remote(payload,
                                           (w.rank, num, group_name),
                                           {}))
        views = ray_tpu.get(refs, timeout=300)
        # Every rank must see the SAME global world or the gang mesh
        # (and every collective under it) is built on sand — a rank
        # that attached to a stale jax.distributed world fails here
        # with a nameable cause instead of hanging in its first psum.
        base = views[0]
        for rank, v in enumerate(views[1:], start=1):
            if v != base:
                raise RuntimeError(
                    f"inconsistent jax world across the gang: rank 0 "
                    f"sees {base}, rank {rank} sees {v}")
        if base["process_count"] != num:
            raise RuntimeError(
                f"jax.distributed world has {base['process_count']} "
                f"processes but the gang has {num} workers")
        from ..util import flight_recorder

        flight_recorder.record("jax_world_up", group=group_name,
                               world=num,
                               devices=base["devices"],
                               devices_per_host=base["local_devices"])

    def on_shutdown(self, worker_group) -> None:
        def _teardown():
            try:
                import jax

                jax.distributed.shutdown()
            except Exception:
                pass
            return True

        try:
            worker_group.execute(_teardown)
        except Exception:
            pass


class TorchBackend(Backend):
    """CPU gloo process group for torch parity workloads (ref:
    train/torch/config.py _TorchBackend)."""

    def on_start(self, worker_group, run_id: str) -> None:
        num = worker_group.num_workers

        # The rendezvous master must live on rank 0's host (ref:
        # train/torch/config.py _setup_torch_process_group — the store
        # binds on worker 0, not the driver).
        def _pick_master():
            import socket as _socket

            from ray_tpu.core.net import get_node_ip_address

            s = _socket.socket()
            s.bind(("", 0))
            port = s.getsockname()[1]
            s.close()
            return f"{get_node_ip_address()}:{port}"

        master = ray_tpu.get(
            worker_group.execute_async_single(worker_group.workers[0],
                                              _pick_master),
            timeout=60)

        def _init(rank: int, world: int, addr: str):
            import os

            host, port = addr.rsplit(":", 1)
            os.environ["MASTER_ADDR"] = host
            os.environ["MASTER_PORT"] = port
            os.environ["RANK"] = str(rank)
            os.environ["WORLD_SIZE"] = str(world)
            import torch.distributed as dist

            if not dist.is_initialized():
                dist.init_process_group("gloo", rank=rank,
                                        world_size=world)
            return True

        refs = []
        from ..core import serialization

        payload = serialization.dumps_code(_init)
        for w in worker_group.workers:
            refs.append(w.actor.run.remote(payload,
                                           (w.rank, num, master), {}))
        ray_tpu.get(refs, timeout=300)

    def on_shutdown(self, worker_group) -> None:
        def _teardown():
            try:
                import torch.distributed as dist

                if dist.is_initialized():
                    dist.destroy_process_group()
            except Exception:
                pass
            return True

        try:
            worker_group.execute(_teardown)
        except Exception:
            pass
