"""Checkpoint — a directory handle on (fsspec-style) storage.

Role-equivalent to the reference's ray.train.Checkpoint (ref:
python/ray/train/_checkpoint.py) and the StorageContext upload/download
plumbing (train/_internal/storage.py).  Local filesystem paths are the
baseline; to_directory/as_directory copy or expose the payload.  Model
state serialization for jax pytrees rides msgpack via flax.serialization
for the single-blob path; the sharded crash-atomic format lives in
``sharded_checkpoint.py`` and is exposed here through
``Checkpoint.is_sharded``/``load_sharded``.

Durability contract (shared with the sharded plane): every write path
stages into a temp name and commits with one ``os.replace``; a
directory counts as a checkpoint only once it carries the commit
marker (or a sharded ``manifest.json``), so ``find_latest_in`` can
never resume from the torn half of a save a SIGKILL interrupted.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import Any, Dict, Optional

# The commit-marker/manifest discipline lives jax-free in
# util/checkpoint_fs (shared with `rt doctor` / `rt checkpoint`);
# re-exported here because train code historically imports it from
# this module.
from ..util.checkpoint_fs import (COMMIT_MARKER,  # noqa: F401
                                  TMP_SUFFIX, atomic_write,
                                  is_committed, mark_committed,
                                  scan_run_dir)


@contextmanager
def _timed_ckpt(metric: str, sharded: bool = False):
    """Attribute checkpoint I/O to the goodput ledger and observe its
    duration histogram (save vs restore, sharded vs blob)."""
    from ..util import goodput

    with goodput.timed_phase(
            "checkpoint", metric,
            "Checkpoint payload save/restore duration.",
            tags={"sharded": "1" if sharded else "0"},
            tag_keys=("sharded",)):
        yield


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rt_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        yield self.path

    # -- sharded-format bridge -------------------------------------------
    @property
    def is_sharded(self) -> bool:
        from .sharded_checkpoint import is_sharded_checkpoint

        return is_sharded_checkpoint(self.path)

    def load_sharded(self, *, mesh=None, specs=None, target=None,
                     validate: bool = True) -> Any:
        """Restore this (sharded-format) checkpoint, resharding onto
        ``mesh`` — see ``sharded_checkpoint.load_sharded``."""
        from .sharded_checkpoint import load_sharded

        return load_sharded(self.path, mesh=mesh, specs=specs,
                            target=target, validate=validate)

    def manifest_meta(self) -> Dict[str, Any]:
        """User metadata stored in a sharded checkpoint's manifest
        (e.g. the training step), or {} for blob checkpoints."""
        from .sharded_checkpoint import read_manifest

        try:
            return dict(read_manifest(self.path).get("meta") or {})
        except Exception:
            return {}

    # -- convenience jax pytree payloads ---------------------------------
    def save_pytree(self, name: str, tree: Any) -> None:
        from flax import serialization

        with _timed_ckpt("rt_train_checkpoint_save_seconds"):
            os.makedirs(self.path, exist_ok=True)
            # Stage + atomic rename: a SIGKILL mid-write must never
            # leave a truncated msgpack under the committed name (the
            # torn-checkpoint failure the drain plane's save race
            # made likely).
            atomic_write(os.path.join(self.path, name + ".msgpack"),
                         serialization.to_bytes(tree))

    def load_pytree(self, name: str, target: Any = None) -> Any:
        from flax import serialization

        with _timed_ckpt("rt_train_checkpoint_restore_seconds"):
            with open(os.path.join(self.path, name + ".msgpack"),
                      "rb") as f:
                data = f.read()
            if target is None:
                return serialization.msgpack_restore(data)
            return serialization.from_bytes(target, data)

    def save_json(self, name: str, obj: Dict) -> None:
        os.makedirs(self.path, exist_ok=True)
        atomic_write(os.path.join(self.path, name + ".json"),
                     json.dumps(obj))

    def load_json(self, name: str) -> Dict:
        with open(os.path.join(self.path, name + ".json")) as f:
            return json.load(f)

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Keeps the latest/top-k checkpoints in a run directory (ref:
    train/_internal/checkpoint_manager.py)."""

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        # abspath: entry paths mix copy-path joins and adopted
        # (already-absolute) dirs — the dedup in register() compares
        # them as strings, and a relative run_dir would let one
        # directory get two entries (and _prune rmtree the live one).
        self.run_dir = os.path.abspath(run_dir)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries: list = []  # (score, index, path)
        self._index = 0
        os.makedirs(run_dir, exist_ok=True)

    def register(self, source_dir: str,
                 metrics: Optional[Dict] = None) -> Checkpoint:
        source = os.path.abspath(source_dir)
        adopted = self._try_adopt(source)
        if adopted is not None:
            dest, idx = adopted
        else:
            self._index += 1
            idx = self._index
            dest = os.path.join(self.run_dir,
                                f"checkpoint_{idx:06d}")
            with _timed_ckpt("rt_train_checkpoint_save_seconds"):
                # Two-phase: copy into a staging dir, mark it
                # committed, then one atomic rename — a crash
                # mid-copytree leaves only an ignorable *.tmp.
                stage = dest + ".tmp"
                shutil.rmtree(stage, ignore_errors=True)
                shutil.copytree(source, stage)
                mark_committed(stage)
                if os.path.isdir(dest):
                    shutil.rmtree(dest, ignore_errors=True)
                os.replace(stage, dest)
        score = None
        if self.score_attribute and metrics:
            score = metrics.get(self.score_attribute)
        # Re-registering the same adopted dir (a re-save of the same
        # step after an elastic restart) must not leave two entries
        # for one path — _prune would "delete the duplicate" and take
        # the live directory with it.
        self._entries = [e for e in self._entries if e[2] != dest]
        self._entries.append((score, idx, dest))
        self._prune()
        return Checkpoint(dest)

    def _try_adopt(self, source: str):
        """A committed checkpoint already living inside the run dir
        under a ``checkpoint_*`` name (the sharded save writes in
        place — every rank contributed, rank 0 committed) is adopted
        as-is instead of being copied onto itself."""
        if os.path.dirname(source) != os.path.abspath(self.run_dir):
            return None
        name = os.path.basename(source)
        if not name.startswith("checkpoint_") or \
                not is_committed(source):
            return None
        try:
            idx = int(name.split("_", 1)[1])
        except ValueError:
            idx = self._index + 1
        self._index = max(self._index, idx)
        return source, idx

    def _prune(self) -> None:
        if self.num_to_keep is None or \
                len(self._entries) <= self.num_to_keep:
            return
        if self.score_attribute:
            reverse = self.score_order == "max"
            ranked = sorted(
                self._entries,
                key=lambda e: (e[0] is None,
                               -e[0] if (reverse and e[0] is not None)
                               else (e[0] if e[0] is not None else 0)))
        else:
            ranked = sorted(self._entries, key=lambda e: -e[1])
        for _score, _idx, path in ranked[self.num_to_keep:]:
            shutil.rmtree(path, ignore_errors=True)
        self._entries = ranked[: self.num_to_keep]

    def latest(self) -> Optional[Checkpoint]:
        """Newest checkpoint whose directory is still committed on
        disk — an entry that turned torn/missing after registration
        (disk fault, manual surgery) silently falls back to the one
        before it rather than wedging the restart loop."""
        for _score, _idx, path in sorted(self._entries,
                                         key=lambda e: -e[1]):
            if is_committed(path):
                return Checkpoint(path)
        return None

    @staticmethod
    def find_latest_in(run_dir: str) -> Optional[Checkpoint]:
        """Resume support: locate the newest COMMITTED checkpoint_*
        dir on disk — staging (*.tmp) and torn (never-committed) dirs
        are skipped, falling back to the previous committed one, so a
        save killed mid-write can never become the resume point."""
        if not os.path.isdir(run_dir):
            return None
        cands = sorted((d for d in os.listdir(run_dir)
                        if d.startswith("checkpoint_")
                        and not d.endswith(".tmp")), reverse=True)
        for name in cands:
            path = os.path.join(run_dir, name)
            if is_committed(path):
                return Checkpoint(path)
        # Legacy fallback: run dirs written BEFORE the commit-marker
        # discipline carry no marker/manifest anywhere — treating
        # them all as torn would silently resume a pre-upgrade run
        # from step 0.  Only when NOTHING in the dir is committed,
        # accept the newest legacy entry that looks complete (has
        # payload and no half-written *.tmp files inside).  A dir
        # with any committed sibling keeps the strict rule: an
        # uncommitted entry there really is a torn save.  Caveat: a
        # NEW-format first save killed between its per-file atomic
        # writes is indistinguishable from a legacy dir here (no
        # marker, no *.tmp) — so the fallback is logged loudly with
        # the dir name and restore-time validation stays the
        # backstop (`rt checkpoint verify` confirms by hand).
        for name in cands:
            path = os.path.join(run_dir, name)
            try:
                files = os.listdir(path)
            except OSError:
                continue
            if files and not any(f.endswith(TMP_SUFFIX)
                                 for f in files):
                import logging

                logging.getLogger("ray_tpu.train").warning(
                    "no committed checkpoint in %s; resuming from "
                    "uncommitted legacy dir %s (pre-commit-marker "
                    "format assumed — run `rt checkpoint verify %s` "
                    "to confirm it is complete)", run_dir, name, path)
                return Checkpoint(path)
        return None
