"""Checkpoint — a directory handle on (fsspec-style) storage.

Role-equivalent to the reference's ray.train.Checkpoint (ref:
python/ray/train/_checkpoint.py) and the StorageContext upload/download
plumbing (train/_internal/storage.py).  Local filesystem paths are the
baseline; to_directory/as_directory copy or expose the payload.  Model
state serialization for jax pytrees rides msgpack via flax.serialization
(orbax integration is a drop-in upgrade at the call site).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import Any, Dict, Optional


@contextmanager
def _timed_ckpt(metric: str):
    """Attribute checkpoint I/O to the goodput ledger and observe its
    duration histogram (save vs restore)."""
    from ..util import goodput

    with goodput.timed_phase(
            "checkpoint", metric,
            "Checkpoint payload save/restore duration."):
        yield


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rt_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        yield self.path

    # -- convenience jax pytree payloads ---------------------------------
    def save_pytree(self, name: str, tree: Any) -> None:
        from flax import serialization

        with _timed_ckpt("rt_train_checkpoint_save_seconds"):
            os.makedirs(self.path, exist_ok=True)
            with open(os.path.join(self.path, name + ".msgpack"),
                      "wb") as f:
                f.write(serialization.to_bytes(tree))

    def load_pytree(self, name: str, target: Any = None) -> Any:
        from flax import serialization

        with _timed_ckpt("rt_train_checkpoint_restore_seconds"):
            with open(os.path.join(self.path, name + ".msgpack"),
                      "rb") as f:
                data = f.read()
            if target is None:
                return serialization.msgpack_restore(data)
            return serialization.from_bytes(target, data)

    def save_json(self, name: str, obj: Dict) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(os.path.join(self.path, name + ".json"), "w") as f:
            json.dump(obj, f)

    def load_json(self, name: str) -> Dict:
        with open(os.path.join(self.path, name + ".json")) as f:
            return json.load(f)

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Keeps the latest/top-k checkpoints in a run directory (ref:
    train/_internal/checkpoint_manager.py)."""

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries: list = []  # (score, index, path)
        self._index = 0
        os.makedirs(run_dir, exist_ok=True)

    def register(self, source_dir: str,
                 metrics: Optional[Dict] = None) -> Checkpoint:
        self._index += 1
        dest = os.path.join(self.run_dir,
                            f"checkpoint_{self._index:06d}")
        if os.path.abspath(source_dir) != dest:
            with _timed_ckpt("rt_train_checkpoint_save_seconds"):
                shutil.copytree(source_dir, dest, dirs_exist_ok=True)
        score = None
        if self.score_attribute and metrics:
            score = metrics.get(self.score_attribute)
        self._entries.append((score, self._index, dest))
        self._prune()
        return Checkpoint(dest)

    def _prune(self) -> None:
        if self.num_to_keep is None or \
                len(self._entries) <= self.num_to_keep:
            return
        if self.score_attribute:
            reverse = self.score_order == "max"
            ranked = sorted(
                self._entries,
                key=lambda e: (e[0] is None,
                               -e[0] if (reverse and e[0] is not None)
                               else (e[0] if e[0] is not None else 0)))
        else:
            ranked = sorted(self._entries, key=lambda e: -e[1])
        for _score, _idx, path in ranked[self.num_to_keep:]:
            shutil.rmtree(path, ignore_errors=True)
        self._entries = ranked[: self.num_to_keep]

    def latest(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        latest = max(self._entries, key=lambda e: e[1])
        return Checkpoint(latest[2])

    @staticmethod
    def find_latest_in(run_dir: str) -> Optional[Checkpoint]:
        """Resume support: locate the newest checkpoint_* dir on disk."""
        if not os.path.isdir(run_dir):
            return None
        cands = sorted(d for d in os.listdir(run_dir)
                       if d.startswith("checkpoint_"))
        if not cands:
            return None
        return Checkpoint(os.path.join(run_dir, cands[-1]))
