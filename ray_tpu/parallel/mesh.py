"""Device mesh construction with standard parallelism axes.

TPU-native design (no reference counterpart — the reference has no mesh
concept; its parallelism is process groups).  Axis vocabulary follows the
scaling playbook: ``data`` (DP), ``fsdp`` (sharded optimizer/params over
DCN or ICI), ``tensor`` (TP over ICI), ``seq`` (context/sequence
parallel), ``pipeline`` (PP), ``expert`` (MoE).  A MeshSpec names the
axes and sizes; create_mesh lays devices out so the fastest-varying axes
(tensor, seq) land on physically adjacent ICI neighbours, which is what
jax.experimental.mesh_utils optimizes for on real TPU topologies.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# ``dcn`` is the outermost (slowest) axis: data-parallel replicas
# across TPU SLICES communicate over the data-center network, while
# every axis to its right stays inside a slice on ICI (ref: the
# multi-slice mesh recipe — gradient all-reduce hierarchically: ICI
# within a slice, DCN across slices).
AXIS_ORDER = ("dcn", "data", "fsdp", "expert", "pipeline", "seq",
              "tensor")


@dataclass
class MeshSpec:
    """Named parallelism degrees; -1 on one axis means "all remaining"."""

    dcn: int = 1
    data: int = 1
    fsdp: int = 1
    expert: int = 1
    pipeline: int = 1
    seq: int = 1
    tensor: int = 1

    def axes(self) -> Dict[str, int]:
        return {name: getattr(self, name) for name in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.axes()
        wildcard = [k for k, v in sizes.items() if v == -1]
        if len(wildcard) > 1:
            raise ValueError("only one axis may be -1")
        known = 1
        for k, v in sizes.items():
            if v != -1:
                if v <= 0:
                    raise ValueError(f"axis {k} has invalid size {v}")
                known *= v
        if wildcard:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {known}")
            sizes[wildcard[0]] = n_devices // known
        else:
            if known != n_devices:
                raise ValueError(
                    f"mesh {sizes} needs {known} devices, have {n_devices}")
        return MeshSpec(**sizes)

    def nontrivial_axes(self) -> Tuple[str, ...]:
        return tuple(k for k, v in self.axes().items() if v > 1)


def create_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a jax.sharding.Mesh for the spec over the given devices
    (default: all global devices, honoring jax.distributed worlds)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    spec = spec.resolve(len(devices))
    sizes = spec.axes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils

        # Topology-aware layout on real TPU slices (ICI-adjacent tensor/
        # seq axes); falls back below for virtual CPU meshes.
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices))
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def local_mesh(spec: Optional[MeshSpec] = None):
    """Mesh over this process's addressable devices only (single-host)."""
    import jax

    devices = jax.local_devices()
    if spec is None:
        spec = MeshSpec(data=len(devices))
    return create_mesh(spec, devices)


def process_contiguous_devices() -> List:
    """Global devices in process-major order (all of process 0, then
    process 1, ...).  jax.devices() is already sorted this way, but
    the multi-host training plane's slice math DEPENDS on it, so the
    ordering is enforced here rather than assumed."""
    import jax

    return sorted(jax.devices(),
                  key=lambda d: (d.process_index, d.id))


def gang_mesh(axis_sizes: Dict[str, int],
              devices: Optional[Sequence] = None):
    """Process-contiguous mesh over a gang: a plain C-order reshape of
    the process-major device list into the named ``axis_sizes``
    (insertion order = slowest..fastest varying).

    Deliberately NOT ``mesh_utils.create_device_mesh``: its topology
    optimization may permute devices, and the multi-host training
    plane needs rank r's devices to occupy a CONTIGUOUS block of
    flattened mesh coordinates — the invariant that makes per-rank
    global-batch slices and the sharded checkpoint plane's
    ``coords_for_rank`` agree with the mesh.  On real TPU slices,
    process-major C-order already lands the fastest (rightmost) axis
    on intra-host ICI, which is what the default fsdp x tensor policy
    wants."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = process_contiguous_devices()
    devices = list(devices)
    names = tuple(axis_sizes)
    shape = tuple(int(axis_sizes[a]) for a in names)
    n = 1
    for s in shape:
        n *= s
    if n != len(devices):
        raise ValueError(
            f"mesh {dict(axis_sizes)} needs {n} devices, gang has "
            f"{len(devices)}")
    return Mesh(np.array(devices, dtype=object).reshape(shape), names)
