"""Pipeline parallelism — GPipe microbatch rotation over the mesh.

Fills the reference's PP gap (SURVEY.md §2.3: absent as a training
feature; its compiled-DAG actor pipelines are a building block, not a
trainer).  TPU-native shape: every pipeline stage lives on one slice of
the ``pipeline`` mesh axis, stage parameters are stacked on a leading
stage dim sharded over that axis, and a lax.scan rotates activations to
the next stage with ppermute each tick.  Bubble fraction is the usual
(S-1)/(M+S-1); autodiff through the scan yields 1F1B-ish memory with
jax.checkpoint on the stage fn.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp


def pipeline_apply(stage_fn: Callable, stage_params, x, *,
                   num_microbatches: int, axis_name: str = "pipeline",
                   checkpoint_stage: bool = True):
    """Run a pipeline of S stages over a batch, inside shard_map.

    stage_fn(params_for_stage, activation) -> activation (same shape!)
    stage_params: pytree whose leaves have the *local* stage's values
        (shard_map already sliced the stacked [S, ...] leaves).
    x: local full-batch input [batch, ...] — every stage receives the
        same x operand, only stage 0 actually consumes it.
    Returns activations after the last stage, valid on every device
    (masked psum broadcast), shape [batch, ...].
    """
    s = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    # shard_map slices the stacked [S, ...] leaves to [1, ...] locally;
    # strip that stage dim so stage_fn sees clean per-stage params.
    stage_params = jax.tree_util.tree_map(
        lambda a: jax.lax.squeeze(a, (0,)), stage_params)
    m = num_microbatches
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible by microbatches {m}")
    mb = b // m
    micro = x.reshape((m, mb) + x.shape[1:])

    fn = stage_fn
    if checkpoint_stage:
        fn = jax.checkpoint(stage_fn)

    perm_fwd = [(j, (j + 1) % s) for j in range(s)]
    total = m + s - 1

    def tick(carry, t):
        acts, outputs = carry
        # Stage 0 injects microbatch t (while valid); others use the
        # activation received on the previous tick.
        mb_idx = jnp.clip(t, 0, m - 1)
        inject = jax.lax.dynamic_index_in_dim(micro, mb_idx, 0,
                                              keepdims=False)
        inp = jnp.where(stage == 0, inject, acts)
        out = fn(stage_params, inp)
        # Last stage records its result at position t-(s-1) when valid.
        out_idx = jnp.clip(t - (s - 1), 0, m - 1)
        is_valid = jnp.logical_and(stage == s - 1, t >= s - 1)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, out.astype(outputs.dtype), out_idx, 0)
        outputs = jnp.where(is_valid, updated, outputs)
        # Rotate activations to the next stage.
        acts = jax.lax.ppermute(out, axis_name, perm_fwd)
        return (acts, outputs), None

    acts0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    outputs0 = jnp.zeros((m, mb) + x.shape[1:], x.dtype)
    (_, outputs), _ = jax.lax.scan(tick, (acts0, outputs0),
                                   jnp.arange(total))
    # Broadcast the last stage's outputs to all stages so downstream
    # (loss on every data-parallel replica) sees them.
    outputs = jax.lax.psum(
        jnp.where(stage == s - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape((b,) + x.shape[1:])


def stack_stage_params(params_per_stage):
    """Stack a list of per-stage pytrees into one pytree with a leading
    stage dim (shard it over the ``pipeline`` axis)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *params_per_stage)
