"""Ring attention — context parallelism over the ICI ring.

Fills the reference's sequence-parallel gap (SURVEY.md §5.7: absent
upstream, first-class here).  Sequence is sharded over the ``seq`` mesh
axis; K/V blocks rotate around the ring via ppermute while each device
accumulates online-softmax partial attention for its resident Q block —
blockwise attention in the ring-attention style (Liu et al.), expressed
as a lax.scan inside shard_map so XLA overlaps the permute with compute.

Differentiable by construction (autodiff through scan + ppermute; the
transpose of ppermute is the reverse rotation), with jax.checkpoint on
the per-step body so activation memory stays O(seq_local) per device.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _block_attn(q, k, v, q_off, kv_off, causal, scale):
    """One (Q_local x KV_block) online-softmax partial.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D].  Returns (num, den, m) partials
    in fp32: num [B,Tq,H,D], den [B,Tq,H], m [B,Tq,H].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        q_idx = q_off + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        kv_idx = kv_off + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        mask = q_idx >= kv_idx
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)                      # [B,H,Tq]
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(m[..., None] <= _NEG_INF / 2, 0.0, p)
    den = jnp.sum(p, axis=-1)                          # [B,H,Tq]
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    # Rearrange to [B,Tq,H,...]
    return num, den.transpose(0, 2, 1), m.transpose(0, 2, 1)


def _merge(num, den, m, num2, den2, m2):
    m_new = jnp.maximum(m, m2)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.exp(m2 - m_new)
    num = num * a1[..., None] + num2 * a2[..., None]
    den = den * a1 + den2 * a2
    return num, den, m_new


def ring_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True,
                   scale: Optional[float] = None,
                   checkpoint_steps: Optional[bool] = None,
                   impl: str = "flash",
                   block_q: int = 256, block_k: int = 256):
    """Attention over a sequence sharded on ``axis_name``.

    Must be called inside shard_map (or pmap) with q/k/v local shards of
    shape [batch, seq_local, heads, head_dim].  Returns the local output
    shard, same shape/dtype as q.

    ``impl="flash"`` (default) computes each ring step's blockwise
    attention with the Pallas flash kernel (ops/flash_attention.py) and
    merges normalized partials by log-sum-exp weights, so long-context
    SP runs at flash throughput; the ppermute of the next K/V block is
    issued before the step's kernel, letting XLA overlap the ICI
    transfer with MXU compute.  ``impl="lax"`` keeps the plain-lax
    online-softmax path (reference semantics / debugging).

    ``checkpoint_steps`` defaults per impl: False for flash (the
    kernel's custom vjp already keeps only O(seq_local) residuals per
    step — k/v blocks, partial out, lse — so remat would just rerun
    the forward kernel in the backward for nothing) and True for lax
    (whose step materializes [Tq, Tk] score blocks).
    """
    if impl == "flash":
        if checkpoint_steps is None:
            checkpoint_steps = False
        return _ring_attention_flash(q, k, v, axis_name=axis_name,
                                     causal=causal, scale=scale,
                                     checkpoint_steps=checkpoint_steps,
                                     block_q=block_q, block_k=block_k)
    if checkpoint_steps is None:
        checkpoint_steps = True
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    q32 = q.astype(jnp.float32)

    def step(carry, i):
        kv, num, den, m = carry
        k_blk, v_blk = kv
        src = (rank - i) % n      # whose block we currently hold
        num2, den2, m2 = _block_attn(
            q32, k_blk.astype(jnp.float32), v_blk.astype(jnp.float32),
            q_off=rank * t_local, kv_off=src * t_local,
            causal=causal, scale=scale)
        num, den, m = _merge(num, den, m, num2, den2, m2)
        # Rotate K/V to the next device (i -> i+1 around the ring).
        perm = [(j, (j + 1) % n) for j in range(n)]
        kv = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), (k_blk, v_blk))
        return (kv, num, den, m), None

    if checkpoint_steps:
        step = jax.checkpoint(step)

    num0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    den0 = jnp.zeros((b, t_local, h), jnp.float32)
    m0 = jnp.full((b, t_local, h), _NEG_INF, jnp.float32)
    (_, num, den, m), _ = jax.lax.scan(
        step, ((k, v), num0, den0, m0), jnp.arange(n))
    den = jnp.where(den == 0.0, 1.0, den)
    out = num / den[..., None]
    return out.astype(q.dtype)


def _ring_attention_flash(q, k, v, *, axis_name: str, causal: bool,
                          scale: Optional[float],
                          checkpoint_steps: bool,
                          block_q: int, block_k: int):
    """Flash-kernel ring attention (round-2 VERDICT item 3).

    Each ring step runs the Pallas kernel on (Q_local, KV_block) and
    merges NORMALIZED partial outputs with their log-sum-exps:
        lse' = logaddexp(lse_acc, lse_blk)
        out' = out_acc*exp(lse_acc-lse') + out_blk*exp(lse_blk-lse')
    Block-level causality is decided per step (src ring position vs our
    rank): blocks strictly before us are dense, our own block is
    in-kernel causal, blocks after us are skipped — a lax.switch, so
    the skipped branch costs nothing on device.
    """
    from ..ops.flash_attention import flash_attention_with_lse

    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if scale is not None and abs(scale - d ** -0.5) > 1e-9:
        raise ValueError("flash impl uses the standard 1/sqrt(d) scale")

    def partial_flash(k_blk, v_blk, blk_causal: bool):
        out, lse = flash_attention_with_lse(
            q, k_blk, v_blk, causal=blk_causal,
            block_q=block_q, block_k=block_k)
        return out.astype(jnp.float32), lse

    def step(carry, i):
        (k_blk, v_blk), out_acc, lse_acc = carry
        # Issue the rotation FIRST so the ICI transfer of the next K/V
        # block overlaps this step's kernel (scan keeps the data
        # dependency: the permuted block is only consumed next step).
        perm = [(j, (j + 1) % n) for j in range(n)]
        kv_next = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm),
            (k_blk, v_blk))
        src = (rank - i) % n      # whose block we currently hold

        def merge(args):
            out_blk, lse_blk = args
            lse_new = jnp.logaddexp(lse_acc, lse_blk)
            w1 = jnp.exp(lse_acc - lse_new)
            w2 = jnp.exp(lse_blk - lse_new)
            return (out_acc * w1[..., None] + out_blk * w2[..., None],
                    lse_new)

        def do_dense(_):
            return merge(partial_flash(k_blk, v_blk, False))

        def do_diag(_):
            return merge(partial_flash(k_blk, v_blk, causal))

        def do_skip(_):
            return out_acc, lse_acc

        if causal:
            case = jnp.where(src == rank, 1,
                             jnp.where(src < rank, 0, 2))
            out_acc, lse_acc = jax.lax.switch(
                case, [do_dense, do_diag, do_skip], None)
        else:
            out_acc, lse_acc = do_dense(None)
        return (kv_next, out_acc, lse_acc), None

    if checkpoint_steps:
        step = jax.checkpoint(step)

    out0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    lse0 = jnp.full((b, t_local, h), _NEG_INF, jnp.float32)
    (_, out, _), _ = jax.lax.scan(step, ((k, v), out0, lse0),
                                  jnp.arange(n))
    return out.astype(q.dtype)


def ring_attention_sharded(mesh, q, k, v, *, causal: bool = True,
                           rules=None):
    """Convenience wrapper: runs ring_attention under shard_map on
    ``mesh`` with batch over (data, fsdp) and sequence over ``seq``."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(("data", "fsdp"), "seq", "tensor", None)
    fn = shard_map(
        functools.partial(ring_attention, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
