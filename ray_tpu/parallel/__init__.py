"""ray_tpu.parallel — GSPMD parallelism over TPU device meshes.

The TPU-native replacement for everything the reference delegates to
torch.distributed/NCCL (ref: SURVEY.md §2.3): data/FSDP/tensor parallelism
as sharding rules over a jax.sharding.Mesh, pipeline parallelism as a
shard_map microbatch rotation, and context parallelism (ring attention,
Ulysses all-to-all) — absent from the reference (§5.7) and first-class
here.
"""

from .mesh import (MeshSpec, create_mesh, gang_mesh,  # noqa: F401
                   local_mesh, process_contiguous_devices)
from .sharding import (ShardingRules, logical_sharding,  # noqa: F401
                       shard_pytree, with_logical_constraint)
from .partition_rules import (match_partition_rules,  # noqa: F401
                              named_tree_map, prune_spec, shard_tree,
                              tree_shardings)
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
